"""Cross-rank step tracing: clock alignment, timeline merge, skew
attribution, and the flight recorder.

Covers the tracing plane end to end over the REAL HTTP plumbing where it
matters: two simulated ranks with deliberately skewed clocks ship spans
through the real ``PUT /trace`` route, and the merged ``GET /timeline``
must restore their true ordering; a deliberately delayed rank (the
``worker.step`` faults point) must show up in the skew gauges with the
injected delay; every flight-recorder trigger must leave a journal
postmortem.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request

import pytest

from horovod_tpu import abort, faults, metrics, tracing


@pytest.fixture(autouse=True)
def _fresh_planes(monkeypatch):
    metrics.reset_for_testing()
    tracing.reset_for_testing()
    faults.reset()
    abort.reset()
    yield
    faults.reset()
    abort.reset()
    tracing.reset_for_testing()


def _server():
    from horovod_tpu.runner.http.kv_server import RendezvousServer

    srv = RendezvousServer(host="127.0.0.1")
    srv.start()
    return srv


# ---------------------------------------------------------------------------
# Clock alignment
# ---------------------------------------------------------------------------


class TestClockSync:
    def test_offset_and_error_bound(self):
        cs = tracing.ClockSync()
        # Server is 100s ahead; 0.2s RTT symmetric.
        cs.observe(t_send=10.0, t_recv=10.2, t_server=110.1)
        assert cs.offset() == pytest.approx(100.0)
        assert cs.error() == pytest.approx(0.1)
        assert cs.synced()

    def test_minimum_rtt_sample_wins(self):
        cs = tracing.ClockSync()
        # Fat RTT with asymmetric delay gives a biased offset...
        cs.observe(10.0, 12.0, 111.9)  # offset estimate 100.9, err 1.0
        # ...the tight exchange afterwards corrects it.
        cs.observe(20.0, 20.02, 120.01)  # offset 100.0, err 0.01
        assert cs.offset() == pytest.approx(100.0)
        assert cs.error() == pytest.approx(0.01)

    def test_unsynced_defaults(self):
        cs = tracing.ClockSync()
        assert cs.offset() == 0.0
        assert cs.error() is None
        assert not cs.synced()

    def test_heartbeat_reply_carries_server_time_and_syncs(self, monkeypatch):
        """The worker's ordinary heartbeat PUT doubles as the NTP
        exchange: the server's reply stamps its wall clock and the
        worker's ClockSync converges to ~zero offset on loopback."""
        from horovod_tpu.runner.elastic import worker as elastic_worker

        srv = _server()
        try:
            monkeypatch.setenv("HOROVOD_RENDEZVOUS_ADDR", "127.0.0.1")
            monkeypatch.setenv("HOROVOD_RENDEZVOUS_PORT", str(srv.port))
            monkeypatch.setenv("HOROVOD_HOSTNAME", "sync-host")
            monkeypatch.setenv("HOROVOD_RANK", "0")
            ctx = elastic_worker.ElasticWorkerContext()
            assert ctx.send_heartbeat()
            cs = tracing.clock_sync()
            assert cs.synced()
            # Same machine, same clock: offset bounded by the RTT.
            assert abs(cs.offset()) < 1.0
            assert cs.error() is not None and cs.error() < 1.0
            # And the worker-side gauge mirrors it.
            assert metrics.CLOCK_OFFSET.labels().get() == pytest.approx(
                cs.offset())
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Step tracer + spans
# ---------------------------------------------------------------------------


class TestStepTracer:
    def test_step_scope_records_spans_and_step(self):
        tr = tracing.get_tracer()
        with tr.step_scope("train_step") as rec:
            with tracing.span("forward", "phase"):
                pass
            with tracing.span("allreduce", "collective"):
                pass
        assert rec.step == 1
        steps = tr.ring_snapshot()
        assert len(steps) == 1
        names = [s["name"] for s in steps[0]["spans"]]
        assert names[0] == "train_step"  # the step span leads
        assert "forward" in names and "allreduce" in names

    def test_ring_keeps_last_k(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TRACE_RING_STEPS", "3")
        tracing.reset_for_testing()
        tr = tracing.get_tracer()
        for _ in range(7):
            with tr.step_scope("train_step"):
                pass
        steps = [s["step"] for s in tr.ring_snapshot()]
        assert steps == [5, 6, 7]

    def test_span_cap_counts_drops(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TRACE_MAX_SPANS", "4")
        tracing.reset_for_testing()
        tr = tracing.get_tracer()
        with tr.step_scope("train_step"):
            for i in range(10):
                tr.record(f"s{i}", "phase", 0.0, 0.001)
        (steprec,) = tr.ring_snapshot()
        assert len(steprec["spans"]) <= 5  # cap + the step span
        assert steprec["dropped_spans"] >= 6

    def test_ambient_spans_collect_outside_steps(self):
        tr = tracing.get_tracer()
        with tracing.span("allreduce", "collective"):
            pass
        snap = tr.ring_snapshot()
        assert snap and snap[-1]["kind"] == "eager"
        assert snap[-1]["spans"][0]["name"] == "allreduce"

    def test_open_spans_in_flight_snapshot(self):
        tr = tracing.get_tracer()
        token = tr.begin_span("wedged_allreduce", "collective")
        snap = tr.flight_snapshot()
        assert [o["name"] for o in snap["open_spans"]] == [
            "wedged_allreduce"]
        assert snap["open_spans"][0]["age_s"] >= 0.0
        tr.end_span(token)
        assert tr.flight_snapshot()["open_spans"] == []

    def test_payload_wire_format(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_RANK", "3")
        monkeypatch.setenv("HOROVOD_HOSTNAME", "payload-host")
        tr = tracing.get_tracer()
        with tr.step_scope("train_step"):
            pass
        p = tr.payload()
        assert p["rank"] == "3" and p["host"] == "payload-host"
        assert "clock_offset_s" in p and isinstance(p["steps"], list)
        json.dumps(p)  # must be wire-serializable


# ---------------------------------------------------------------------------
# Cross-rank merge e2e (real HTTP, injected clock skew)
# ---------------------------------------------------------------------------


class TestTimelineMerge:
    def _simulate_rank(self, srv, rank, host, clock_skew, start_delay,
                       monkeypatch):
        """One simulated worker: a skewed wall clock, a real heartbeat
        exchange measuring the offset, one traced step shipped through
        the real PUT /trace route."""
        from horovod_tpu.runner.http.kv_server import KVClient

        clock = lambda: time.time() + clock_skew  # noqa: E731
        cs = tracing.ClockSync(clock=clock)
        client = KVClient("127.0.0.1", srv.port)
        # Real NTP-style exchange over HTTP (timestamps on the SKEWED
        # clock, server time from the reply).
        for _ in range(3):
            t0 = clock()
            reply = client.put("heartbeat", host,
                               json.dumps({"rank": rank}).encode())
            t1 = clock()
            cs.observe(t0, t1, json.loads(reply)["t_server"])
        tracer = tracing.StepTracer(cs)
        if start_delay:
            time.sleep(start_delay)
        with tracer.step_scope("train_step"):
            with_span_clock = cs.now()
            tracer.record("allreduce", "collective", with_span_clock, 0.01)
        monkeypatch.setenv("HOROVOD_RANK", str(rank))
        monkeypatch.setenv("HOROVOD_HOSTNAME", host)
        payload = tracer.payload()
        client.put(tracing.TRACE_SCOPE, host, json.dumps(payload).encode())
        return payload

    def test_merged_timeline_corrects_injected_skew(self, monkeypatch):
        """Rank 1's clock runs 120s ahead of rank 0's, but it actually
        starts ~0.3s later. The merged /timeline must order the two
        ranks by TRUE time (0.3s apart), not raw clocks (120s apart)."""
        srv = _server()
        try:
            self._simulate_rank(srv, 0, "rank0-host", clock_skew=0.0,
                                start_delay=0.0, monkeypatch=monkeypatch)
            self._simulate_rank(srv, 1, "rank1-host", clock_skew=120.0,
                                start_delay=0.3, monkeypatch=monkeypatch)
            url = f"http://127.0.0.1:{srv.port}/timeline"
            with urllib.request.urlopen(url, timeout=10) as r:
                assert r.status == 200
                merged = json.loads(r.read())
            spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
            assert {e["pid"] for e in spans} == {0, 1}
            t0 = min(e["ts"] for e in spans if e["pid"] == 0
                     and e["name"] == "allreduce")
            t1 = min(e["ts"] for e in spans if e["pid"] == 1
                     and e["name"] == "allreduce")
            delta_s = (t1 - t0) / 1e6
            # True separation ~0.3s; raw clocks would say ~120.3s. Allow
            # generous slack for loopback RTT error + scheduling.
            assert 0.05 < delta_s < 2.0, (
                f"offset correction failed: corrected delta {delta_s}s")
            # Track metadata: one named process per rank.
            names = {e["args"]["name"] for e in merged["traceEvents"]
                     if e.get("name") == "process_name"}
            assert names == {"rank 0 (rank0-host)", "rank 1 (rank1-host)"}
        finally:
            srv.stop()

    def test_timeline_unauthenticated_even_with_secret(self, monkeypatch):
        """Trace viewers can't HMAC: /timeline and /stragglers share the
        /metrics auth exemption while the KV surface stays 403."""
        import urllib.error

        from horovod_tpu.runner import secret as _secret

        monkeypatch.setenv(_secret.ENV_KEY, _secret.make_secret_key())
        srv = _server()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            for route in ("/timeline", "/stragglers"):
                with urllib.request.urlopen(base + route, timeout=10) as r:
                    assert r.status == 200
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/_version", timeout=10)
            assert ei.value.code == 403
        finally:
            srv.stop()

    def test_malformed_trace_payload_tolerated(self):
        from horovod_tpu.runner.http.kv_server import KVClient

        srv = _server()
        try:
            client = KVClient("127.0.0.1", srv.port)
            client.put(tracing.TRACE_SCOPE, "bad-host", b"not json")
            client.put(tracing.TRACE_SCOPE, "odd-host",
                       json.dumps({"rank": "0", "steps": [
                           {"spans": [{"cat": "collective"}]}]}).encode())
            merged = srv.timeline_json()
            assert merged["traceEvents"] is not None  # renders, no crash
            assert srv.straggler_summary()["matched"] == 0
        finally:
            srv.stop()

    def test_oversized_trace_payload_rejected(self):
        import urllib.error

        from horovod_tpu.runner.http.kv_server import KVClient

        srv = _server()
        try:
            client = KVClient("127.0.0.1", srv.port, retries=1)
            with pytest.raises(urllib.error.HTTPError) as ei:
                client.put(tracing.TRACE_SCOPE, "fat-host",
                           b"x" * (2 << 20))
            assert ei.value.code == 413
        finally:
            srv.stop()

    def test_clear_heartbeat_drops_trace_payload(self):
        from horovod_tpu.runner.http.kv_server import KVClient

        srv = _server()
        try:
            client = KVClient("127.0.0.1", srv.port)
            client.put(tracing.TRACE_SCOPE, "gone-host",
                       json.dumps({"rank": "0", "steps": []}).encode())
            assert srv.trace_payload("gone-host") is not None
            srv.clear_heartbeat("gone-host")
            assert srv.trace_payload("gone-host") is None
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Skew attribution
# ---------------------------------------------------------------------------


class TestSkewAttribution:
    def test_compute_skew_math(self):
        payloads = {
            "hA": {"rank": "0", "clock_offset_s": 0.0, "steps": [
                {"step": 7, "spans": [
                    {"name": "allreduce", "cat": "collective",
                     "t": 100.0, "dur": 0.5}]}]},
            "hB": {"rank": "1", "clock_offset_s": -5.0, "steps": [
                {"step": 7, "spans": [
                    {"name": "allreduce", "cat": "collective",
                     "t": 105.3, "dur": 0.2}]}]},
        }
        skew = tracing.compute_skew(payloads)
        assert skew["matched"] == 1
        assert skew["worst"]["last_rank"] == "1"
        assert skew["worst"]["last_host"] == "hB"
        assert skew["worst"]["skew_s"] == pytest.approx(0.3)
        assert skew["ranks"]["1"]["max_lateness_s"] == pytest.approx(0.3)
        assert skew["ranks"]["0"]["max_lateness_s"] == 0.0

    def test_cross_generation_spans_never_match(self):
        """A zombie's pre-recovery spans (older generation) must not
        match — or skew — the re-formed world's."""
        span = {"name": "allreduce", "cat": "collective",
                "t": 100.0, "dur": 0.1}
        payloads = {
            "hA": {"rank": "0", "generation": 2, "steps": [
                {"step": 1, "spans": [dict(span)]}]},
            "hB": {"rank": "1", "generation": 3, "steps": [
                {"step": 1, "spans": [dict(span, t=150.0)]}]},
        }
        skew = tracing.compute_skew(payloads)
        assert skew["matched"] == 0 and skew["worst"] is None

    def test_rebase_zeroes_counter_keeps_ring(self):
        """World (re-)join rebases the step counter (so generation
        members count from one point) without dropping flight history."""
        tr = tracing.get_tracer()
        for _ in range(3):
            with tr.step_scope("train_step"):
                pass
        assert tr.steps_recorded() == 3
        tr.rebase()
        assert tr.steps_recorded() == 0
        assert len(tr.ring_snapshot()) == 3  # history survives
        with tr.step_scope("train_step") as rec:
            pass
        assert rec.step == 1

    def test_unmatched_spans_ignored(self):
        payloads = {
            "hA": {"rank": "0", "steps": [
                {"step": 1, "spans": [
                    {"name": "only_here", "cat": "collective",
                     "t": 1.0, "dur": 0.1}]}]},
        }
        skew = tracing.compute_skew(payloads)
        assert skew["matched"] == 0 and skew["worst"] is None

    def test_skew_gauges_exact_for_delayed_rank(self, monkeypatch):
        """A rank deliberately delayed via the faults plane
        (``worker.step=delay``) must show up in the /metrics skew gauges
        with approximately the injected delay, named as the last
        arriver."""
        from horovod_tpu.runner.http.kv_server import KVClient

        delay_s = 0.4
        # 2nd firing only: rank 0's step fires hit 1 (clean), rank 1's
        # fires hit 2 (delayed) — the deterministic per-hit window.
        faults.inject(faults.WORKER_STEP, "delay", arg=delay_s, at=2)
        srv = _server()
        try:
            client = KVClient("127.0.0.1", srv.port)
            for rank, host in ((0, "fast-host"), (1, "slow-host")):
                tracer = tracing.StepTracer(tracing.ClockSync())
                faults.fire(faults.WORKER_STEP)  # the step dispatch gate
                with tracer.step_scope("train_step"):
                    tracer.record("allreduce", "collective",
                                  tracer.clock.now(), 0.01)
                payload = dict(tracer.payload(), rank=str(rank), host=host)
                client.put(tracing.TRACE_SCOPE, host,
                           json.dumps(payload).encode())
            parsed = metrics.validate_prometheus_text(srv.metrics_text())
            skews = {l["rank"]: v for l, v in
                     parsed["hvd_collective_skew_seconds"]["samples"]}
            assert skews["0"] == pytest.approx(0.0, abs=0.15)
            assert skews["1"] == pytest.approx(delay_s, abs=0.25)
            scores = {l["host"]: v for l, v in
                      parsed["hvd_straggler_score"]["samples"]}
            assert scores["slow-host"] > scores.get("fast-host", 0.0)
            worst = srv.straggler_summary()["worst"]
            assert worst["last_rank"] == "1"
            assert worst["last_host"] == "slow-host"
        finally:
            srv.stop()

    def test_straggler_journal_event_throttled(self, tmp_path, monkeypatch):
        """Crossing HOROVOD_STRAGGLER_WARN_SKEW journals one
        straggler_detected per (generation, rank), not one per scrape."""
        from horovod_tpu.runner.http.kv_server import KVClient

        ev = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
        monkeypatch.setenv("HOROVOD_STRAGGLER_WARN_SKEW", "0.1")
        srv = _server()
        try:
            client = KVClient("127.0.0.1", srv.port)
            for rank, host, t in (("0", "hA", 100.0), ("1", "hB", 100.5)):
                client.put(tracing.TRACE_SCOPE, host, json.dumps({
                    "rank": rank, "clock_offset_s": 0.0, "steps": [
                        {"step": 1, "spans": [
                            {"name": "allreduce", "cat": "collective",
                             "t": t, "dur": 0.1}]}]}).encode())
            srv.metrics_text()
            srv.metrics_text()  # second scrape: must not re-journal
            events = [json.loads(l) for l in ev.read_text().splitlines()]
            stragglers = [e for e in events
                          if e["event"] == "straggler_detected"]
            assert len(stragglers) == 1
            assert stragglers[0]["rank"] == "1"
            assert stragglers[0]["skew_s"] == pytest.approx(0.5)
        finally:
            srv.stop()
            monkeypatch.delenv("HOROVOD_EVENT_LOG")
            # Drop the journal handle so later tests get fresh files.
            metrics.journal()


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


def _read_events(path) -> list[dict]:
    return [json.loads(l) for l in open(path).read().splitlines()]


class TestFlightRecorder:
    def _arm_ring(self, n=3):
        tr = tracing.get_tracer()
        for _ in range(n):
            with tr.step_scope("train_step"):
                with tracing.span("allreduce", "collective"):
                    pass
        return tr

    def test_abort_consume_dumps_flight_record(self, tmp_path, monkeypatch):
        ev = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
        self._arm_ring()
        abort.trigger_local("peer died")
        abort.consume()
        frs = [e for e in _read_events(ev)
               if e["event"] == "flight_record"]
        assert len(frs) == 1
        fr = frs[0]
        assert fr["reason"] == "abort_consumed"
        assert fr["detail"] == "peer died"
        assert len(fr["steps"]) == 3
        assert fr["steps"][-1]["spans"][0]["name"] == "train_step"
        assert metrics.FLIGHT_DUMPS.labels(
            reason="abort_consumed").get() == 1
        monkeypatch.delenv("HOROVOD_EVENT_LOG")
        metrics.journal()

    def test_unarmed_consume_does_not_dump(self, tmp_path, monkeypatch):
        ev = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
        self._arm_ring()
        abort.consume()  # hygiene call with nothing armed
        assert not [e for e in (_read_events(ev) if ev.exists() else [])
                    if e["event"] == "flight_record"]
        monkeypatch.delenv("HOROVOD_EVENT_LOG")
        metrics.journal()

    def test_stall_shutdown_dumps_flight_record(self, tmp_path, monkeypatch):
        """The inspector's shutdown path dumps the ring — with the wedged
        ticket's span still OPEN — before interrupting the main thread."""
        from horovod_tpu.stall import StallInspector

        ev = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
        self._arm_ring()
        tr = tracing.get_tracer()
        token = tr.begin_span("wedged_step", "collective")
        inspector = StallInspector(warning_s=0.05, shutdown_s=0.15)
        ticket = inspector.begin("step[wedged]")
        try:
            try:
                time.sleep(8)  # the shutdown SIGINT breaks this sleep
            except KeyboardInterrupt:
                pass
            frs = [e for e in _read_events(ev)
                   if e["event"] == "flight_record"]
            assert frs and frs[0]["reason"] == "stall_shutdown"
            assert "wedged_step" in [o["name"]
                                     for o in frs[0]["open_spans"]]
            assert len(frs[0]["steps"]) == 3
        finally:
            inspector.end(ticket)
            tr.end_span(token)
            inspector.stop()
            abort.reset()
            monkeypatch.delenv("HOROVOD_EVENT_LOG")
            metrics.journal()

    def test_sigterm_drain_dumps_flight_record(self, tmp_path):
        """A real SIGTERM through the elastic drain handler leaves the
        postmortem (subprocess: the handler owns the main thread)."""
        import subprocess
        import sys

        ev = tmp_path / "drain_events.jsonl"
        script = f"""
import json, os, signal, time
os.environ["HOROVOD_EVENT_LOG"] = {str(ev)!r}
from horovod_tpu import tracing
from horovod_tpu.elastic import runner
runner._install_drain_handler()
tr = tracing.get_tracer()
with tr.step_scope("train_step"):
    pass
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(0.5)
assert runner.drain_requested()
"""
        proc = subprocess.run(
            [sys.executable, "-c", script], timeout=120,
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        frs = [e for e in _read_events(ev)
               if e["event"] == "flight_record"]
        assert frs and frs[0]["reason"] == "drain_requested"
        assert frs[0]["steps"]

    def test_ring_depth_covers_last_k_steps(self, tmp_path, monkeypatch):
        """The dump carries exactly the last K steps (the acceptance
        contract: a postmortem of every rank's last K steps)."""
        monkeypatch.setenv("HOROVOD_TRACE_RING_STEPS", "4")
        tracing.reset_for_testing()
        ev = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
        self._arm_ring(n=9)
        snap = tracing.dump_flight_record("test_dump")
        assert [s["step"] for s in snap["steps"]] == [6, 7, 8, 9]
        frs = [e for e in _read_events(ev)
               if e["event"] == "flight_record"]
        assert [s["step"] for s in frs[0]["steps"]] == [6, 7, 8, 9]
        monkeypatch.delenv("HOROVOD_EVENT_LOG")
        metrics.journal()


# ---------------------------------------------------------------------------
# Factory-step integration + profiler surface
# ---------------------------------------------------------------------------


class TestFactoryIntegration:
    def test_sampled_step_ships_to_server(self, monkeypatch):
        """A real make_train_step loop with HOROVOD_TRACE_SAMPLE ships
        the sampled (synced) step through the real PUT /trace route and
        shows up on the merged timeline."""
        import numpy as np
        import optax

        import horovod_tpu as hvd

        srv = _server()
        try:
            monkeypatch.setenv("HOROVOD_TRACE_SAMPLE", "2")
            monkeypatch.setenv("HOROVOD_STALL_CHECK_STEPS", "0")
            monkeypatch.setenv("HOROVOD_RENDEZVOUS_ADDR", "127.0.0.1")
            monkeypatch.setenv("HOROVOD_RENDEZVOUS_PORT", str(srv.port))
            monkeypatch.setenv("HOROVOD_HOSTNAME", "factory-host")
            monkeypatch.setenv("HOROVOD_RANK", "0")
            hvd.init()
            tracing.reset_for_testing()

            def loss_fn(params, batch):
                x, y = batch
                return (((x @ params["w"]) - y) ** 2).mean()

            opt = hvd.DistributedOptimizer(optax.sgd(0.1))
            step = hvd.data_parallel.make_train_step(loss_fn, opt)
            params = hvd.data_parallel.replicate(
                {"w": np.ones((4, 1), np.float32)})
            opt_state = hvd.data_parallel.replicate(opt.init(params))
            batch = hvd.data_parallel.shard_batch(
                (np.ones((8, 4), np.float32),
                 np.zeros((8, 1), np.float32)))
            for _ in range(4):
                params, opt_state, _ = step(params, opt_state, batch)
            deadline = time.time() + 15
            while (time.time() < deadline
                   and srv.trace_payload("factory-host") is None):
                time.sleep(0.1)
            payload = srv.trace_payload("factory-host")
            assert payload is not None, "sampled step never shipped"
            synced = [s["step"] for s in payload["steps"] if s["synced"]]
            assert synced and all(s % 2 == 0 for s in synced)
            spans = [e for e in srv.timeline_json()["traceEvents"]
                     if e.get("ph") == "X"]
            assert any(e["name"] == "train_step" for e in spans)
        finally:
            srv.stop()

    def test_profiler_summary_has_stragglers(self):
        summ = __import__("horovod_tpu").profiler.summary()
        st = summ["stragglers"]
        assert "clock_offset_s" in st
        assert "steps_recorded" in st
        assert "trace_sample" in st

    def test_eager_dispatch_records_collective_span(self):
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        tracing.reset_for_testing()
        n = hvd.size()
        hvd.allreduce(np.ones((n, 4), np.float32), op=hvd.Sum)
        snap = tracing.get_tracer().ring_snapshot()
        all_spans = [sp for s in snap for sp in s["spans"]]
        assert any(sp["name"] == "allreduce"
                   and sp["cat"] == "collective" for sp in all_spans)
