"""Pallas 1x1-conv backward kernels vs jax autodiff (interpret mode on
CPU; the real-chip perf measurements live in docs/benchmarks.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.conv_backward import conv1x1, dw_1x1


def _ref_conv(x, w, strides):
    return jax.lax.conv_general_dilated(
        x, w, strides, "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def test_dw_kernel_matches_exact_matmul():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(6000, 16).astype(np.float32))
    dy = jnp.asarray(rng.randn(6000, 24).astype(np.float32))
    got = np.asarray(dw_1x1(x, dy, tile=1024, interpret=True))
    want = np.asarray(x).T @ np.asarray(dy)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("strides", [(1, 1), (2, 2)])
def test_conv1x1_forward_and_grads_match_autodiff(strides):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 8, 8, 12).astype(np.float32))
    w = jnp.asarray(rng.randn(1, 1, 12, 20).astype(np.float32) * 0.1)

    out = conv1x1(x, w, strides)
    want = _ref_conv(x, w, strides)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)

    def loss_ours(x, w):
        return jnp.sum(conv1x1(x, w, strides) ** 2)

    def loss_ref(x, w):
        return jnp.sum(_ref_conv(x, w, strides) ** 2)

    gx, gw = jax.grad(loss_ours, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=1e-3, atol=1e-3)


def test_conv1x1_bf16_path():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 4, 4, 8).astype(np.float32), jnp.bfloat16)
    w = jnp.asarray(rng.randn(1, 1, 8, 16).astype(np.float32) * 0.1,
                    jnp.bfloat16)
    gw = jax.grad(lambda w: jnp.sum(conv1x1(x, w).astype(jnp.float32)))(w)
    assert gw.dtype == jnp.bfloat16 and gw.shape == (1, 1, 8, 16)
