"""DistributedOptimizer correctness — the analog of the reference's
``test/parallel/test_torch.py`` DistributedOptimizer-vs-manual-averaging
equivalence tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P


def _traced_update(hvd, opt, grads_per_rank, params):
    """Run one optimizer update inside shard_map; grads differ per rank."""
    mesh = hvd.global_mesh()

    def step(g):
        g = jax.tree.map(lambda a: a[0], g)  # strip the shard's stacking axis
        state = opt.init(params)
        updates, _ = opt.update(g, state, params)
        return updates

    f = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=P("hvd"), out_specs=P(), check_vma=False
        )
    )
    return f(grads_per_rank)


def test_distributed_sgd_equals_manual_average(hvd):
    params = {"w": jnp.ones((4,)), "b": jnp.zeros((2,))}
    gw = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    gb = np.random.RandomState(1).randn(8, 2).astype(np.float32)

    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    updates = _traced_update(
        hvd, opt, {"w": gw, "b": gb}, params
    )
    np.testing.assert_allclose(
        np.asarray(updates["w"]), -0.1 * gw.mean(0), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(updates["b"]), -0.1 * gb.mean(0), rtol=1e-5, atol=1e-6
    )


def test_distributed_optimizer_sum_op(hvd):
    params = {"w": jnp.zeros((3,))}
    gw = np.random.RandomState(2).randn(8, 3).astype(np.float32)
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), op=hvd.Sum)
    updates = _traced_update(hvd, opt, {"w": gw}, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), -gw.sum(0), rtol=1e-5)


def test_distributed_optimizer_fp16_compression(hvd):
    params = {"w": jnp.zeros((5,))}
    gw = np.random.RandomState(3).randn(8, 5).astype(np.float32)
    opt = hvd.DistributedOptimizer(
        optax.sgd(1.0), compression=hvd.Compression.fp16
    )
    updates = _traced_update(hvd, opt, {"w": gw}, params)
    # fp16 wire: tolerances loosened accordingly, dtype restored to f32.
    assert updates["w"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(updates["w"]), -gw.mean(0), rtol=1e-2, atol=1e-3
    )


def test_distributed_optimizer_int8_compression(hvd):
    """VERDICT r4 #7 — Compression.int8 (EQuARX-style): the exchange
    becomes quantize -> all_to_all -> dequant-sum -> requant ->
    all_gather. Tolerance bound: two blockwise-int8 round trips, each
    |err| <= block_absmax/127 per element (first trip's errors also
    average over ranks) — assert within 2*absmax/127."""
    params = {"w": jnp.zeros((2000,)), "b": jnp.zeros((7,))}
    rng = np.random.RandomState(4)
    gw = rng.randn(8, 2000).astype(np.float32)
    gb = rng.randn(8, 7).astype(np.float32)
    opt = hvd.DistributedOptimizer(
        optax.sgd(1.0), compression=hvd.Compression.int8
    )
    updates = _traced_update(hvd, opt, {"w": gw, "b": gb}, params)
    assert updates["w"].dtype == jnp.float32
    for got, g in ((updates["w"], gw), (updates["b"], gb)):
        tol = 2.0 * np.abs(g).max() / 127.0
        np.testing.assert_allclose(
            np.asarray(got), -g.mean(0), atol=tol)


def test_int8_training_loss_matches_uncompressed(hvd):
    """Documented loss-match bound: 30 SGD steps on a quadratic, int8
    wire vs none — final losses agree within 5% and both converge."""
    mesh = hvd.global_mesh()
    target = jnp.asarray(np.random.RandomState(5).randn(256).astype(
        np.float32))

    def loss_fn(p, x):
        return jnp.sum((p["w"] * jnp.mean(x) - target) ** 2)

    def run(compression):
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.3), compression=compression)
        p = {"w": jnp.zeros((256,))}
        state = opt.init(p)

        def step(p, state, x):
            l, g = jax.value_and_grad(loss_fn)(p, x)
            updates, state = opt.update(g, state, p)
            return optax.apply_updates(p, updates), state, l

        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P(), P("hvd")),
            out_specs=(P(), P(), P()), check_vma=False))
        x = jnp.ones((8, 2), jnp.float32)
        for _ in range(30):
            p, state, l = f(p, state, x)
        return float(jax.device_get(l).ravel()[0])

    l0 = float(np.sum(np.asarray(target) ** 2))  # loss at w=0
    base = run(hvd.Compression.none)
    quant = run(hvd.Compression.int8)
    assert base < 1e-3 * l0, (base, l0)   # converged >99.9%
    assert quant < 1e-2 * l0, (quant, l0)  # converged under quantization
    # Documented bound: the quantized run lands within 1% of the
    # uncompressed final loss, relative to the initial loss.
    assert abs(quant - base) <= 1e-2 * l0, (base, quant, l0)


def test_int8_hierarchical_mesh(hvd):
    """Compression.int8 inside a step shard_mapped over the hierarchical
    (cross, local) mesh: lax.all_to_all/all_gather accept the tuple axis
    and the quantized mean still lands within the blockwise bound."""
    from horovod_tpu.parallel.hierarchical import (
        HIERARCHICAL_AXES, hierarchical_mesh,
    )

    mesh = hierarchical_mesh(cross_size=2)
    params = {"w": jnp.zeros((600,))}
    gw = np.random.RandomState(7).randn(8, 600).astype(np.float32)
    opt = hvd.DistributedOptimizer(
        optax.sgd(1.0), compression=hvd.Compression.int8)

    def step(g):
        g = jax.tree.map(lambda a: a[0], g)
        state = opt.init(params)
        updates, _ = opt.update(g, state, params)
        return updates

    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=P(HIERARCHICAL_AXES), out_specs=P(),
        check_vma=False))
    updates = f({"w": gw})
    tol = 2.0 * np.abs(gw).max() / 127.0
    np.testing.assert_allclose(
        np.asarray(updates["w"]), -gw.mean(0), atol=tol)


def test_int8_compressor_rejects_plain_wire_use(hvd):
    import pytest as _pytest

    with _pytest.raises(ValueError, match="int8"):
        hvd.Compression.int8.compress(jnp.ones(4))
    with _pytest.raises(ValueError, match="Average/Sum"):
        opt = hvd.DistributedOptimizer(
            optax.sgd(1.0), compression=hvd.Compression.int8,
            op=hvd.Adasum)
        _traced_update(hvd, opt, {"w": np.ones((8, 4), np.float32)},
                       {"w": jnp.zeros((4,))})


def test_backward_passes_per_step_accumulates(hvd):
    """k=2: first microstep produces zero updates; second applies the
    allreduced mean of the accumulated grads."""
    mesh = hvd.global_mesh()
    params = {"w": jnp.zeros((3,))}
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    g1 = np.random.RandomState(4).randn(8, 3).astype(np.float32)
    g2 = np.random.RandomState(5).randn(8, 3).astype(np.float32)

    def two_steps(ga, gb):
        ga, gb = {"w": ga[0]}, {"w": gb[0]}
        state = opt.init(params)
        u1, state = opt.update(ga, state, params)
        u2, state = opt.update(gb, state, params)
        return u1, u2

    f = jax.jit(
        jax.shard_map(
            two_steps,
            mesh=mesh,
            in_specs=(P("hvd"), P("hvd")),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )
    u1, u2 = f(g1, g2)
    np.testing.assert_allclose(np.asarray(u1["w"]), np.zeros(3))
    expected = -((g1 + g2) / 2).mean(0)
    np.testing.assert_allclose(np.asarray(u2["w"]), expected, rtol=1e-5, atol=1e-6)


def test_grad_wrapper_averages(hvd):
    """hvd.grad == DistributedGradientTape parity."""
    mesh = hvd.global_mesh()

    def loss_fn(w, x):
        return jnp.sum(w * x)

    gfn = hvd.grad(loss_fn)
    w = jnp.ones((3,))
    xs = np.random.RandomState(6).randn(8, 3).astype(np.float32)

    f = jax.jit(
        jax.shard_map(
            lambda x: gfn(w, x),
            mesh=mesh,
            in_specs=P("hvd"),
            out_specs=P(),
            check_vma=False,
        )
    )
    np.testing.assert_allclose(np.asarray(f(xs)), xs.mean(0), rtol=1e-5)


def test_invalid_backward_passes(hvd):
    with pytest.raises(ValueError):
        hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=0)
