"""Collective numerics across ranks — the analog of the reference's
``test/parallel/test_torch.py`` op tests: every op is checked against a
local numpy reference computation (SURVEY.md §4 "numerical assertions
pattern"), over multiple dtypes, in both eager (stacked-rank) and traced
(shard_map) regimes, including sub-world process sets.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

DTYPES = [np.float32, np.int32, np.float16]


def _tolerance(dtype):
    return dict(rtol=1e-3, atol=1e-3) if dtype == np.float16 else dict(rtol=1e-6, atol=1e-6)


# -- eager regime (stacked-rank convention) ---------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_allreduce_sum_eager(hvd, dtype):
    x = np.arange(8 * 6, dtype=dtype).reshape(8, 2, 3)
    out = np.asarray(hvd.allreduce(x, op=hvd.Sum))
    expected = np.tile(x.sum(axis=0), (8, 1, 1))
    np.testing.assert_allclose(out, expected, **_tolerance(dtype))


def test_allreduce_average_default(hvd):
    x = np.random.RandomState(0).randn(8, 5).astype(np.float32)
    out = np.asarray(hvd.allreduce(x))
    np.testing.assert_allclose(out, np.tile(x.mean(0), (8, 1)), rtol=1e-6)


def test_allreduce_min_max(hvd):
    x = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Min)), np.tile(x.min(0), (8, 1))
    )
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Max)), np.tile(x.max(0), (8, 1))
    )


def test_allreduce_product(hvd):
    x = np.random.RandomState(2).uniform(0.5, 1.5, (8, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Product)),
        np.tile(x.prod(0), (8, 1)),
        rtol=1e-5,
    )


def test_allreduce_prescale_postscale(hvd):
    x = np.ones((8, 3), dtype=np.float32)
    out = np.asarray(
        hvd.allreduce(x, op=hvd.Sum, prescale_factor=0.5, postscale_factor=2.0)
    )
    np.testing.assert_allclose(out, np.full((8, 3), 8.0))


def test_allreduce_average_bool_compat(hvd):
    x = np.full((8, 2), 2.0, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(hvd.allreduce(x, average=False)), 16.0)
    with pytest.raises(ValueError):
        hvd.allreduce(x, average=True, op=hvd.Sum)


def test_allreduce_shape_validation(hvd):
    with pytest.raises(ValueError, match="stacked-rank"):
        hvd.allreduce(np.zeros((3, 2), np.float32))


def test_allgather_eager(hvd):
    x = np.arange(8 * 2 * 3, dtype=np.float32).reshape(8, 2, 3)
    out = np.asarray(hvd.allgather(x))
    concat = x.reshape(16, 3)
    assert out.shape == (8, 16, 3)
    for r in range(8):
        np.testing.assert_array_equal(out[r], concat)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast_eager(hvd, root):
    x = np.random.RandomState(root).randn(8, 4).astype(np.float32)
    out = np.asarray(hvd.broadcast(x, root_rank=root))
    np.testing.assert_allclose(out, np.tile(x[root], (8, 1)), rtol=1e-6)


def test_broadcast_root_validation(hvd):
    with pytest.raises(ValueError):
        hvd.broadcast(np.zeros((8, 2), np.float32), root_rank=8)


def test_alltoall_eager(hvd):
    # rank r sends chunk j to rank j; chunk = row block of size 1.
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    out = np.asarray(hvd.alltoall(x[:, :, None]))[..., 0]
    np.testing.assert_array_equal(out, x.T)


def test_alltoall_uneven_splits_stacked(hvd):
    # Rank r's chunk j has j+1 rows valued r*100+j; rank i receives i+1
    # rows from every rank (pad-to-max through ONE compiled AllToAll HLO,
    # compacted per row).
    n = 8
    splits = np.arange(1, n + 1, dtype=np.int64)
    total = int(splits.sum())
    x = np.zeros((n, total), np.float32)
    for r in range(n):
        off = 0
        for j in range(n):
            x[r, off: off + j + 1] = r * 100 + j
            off += j + 1
    outs, received = hvd.alltoall(x[:, :, None], splits=splits)
    assert len(outs) == n
    for i in range(n):
        want = np.concatenate(
            [np.full(i + 1, s * 100 + i, np.float32) for s in range(n)])
        np.testing.assert_array_equal(np.asarray(outs[i])[..., 0], want)
        np.testing.assert_array_equal(received[i], np.full(n, i + 1))


def test_alltoall_uneven_splits_matrix(hvd):
    # Per-rank split tables (n, n): rank r sends r rows to rank 0 and the
    # rest to rank 1 (2-rank subset semantics exercised on the world set
    # via zero-padding of the remaining destinations).
    n = 8
    sp = np.zeros((n, n), np.int64)
    sp[:, 0] = np.arange(n)
    sp[:, 1] = n - np.arange(n)
    x = np.zeros((n, n), np.float32)
    for r in range(n):
        x[r, : r] = r * 10  # destined to rank 0
        x[r, r:] = r * 10 + 1  # destined to rank 1
    outs, received = hvd.alltoall(x[:, :, None], splits=sp)
    np.testing.assert_array_equal(received[0], np.arange(n))
    np.testing.assert_array_equal(received[2], np.zeros(n))
    want0 = np.concatenate(
        [np.full(r, r * 10, np.float32) for r in range(n)])
    np.testing.assert_array_equal(np.asarray(outs[0])[..., 0], want0)
    assert np.asarray(outs[2]).size == 0


def test_alltoall_splits_traced_rejected(hvd):
    with pytest.raises(NotImplementedError):
        import jax
        from jax.sharding import PartitionSpec as P

        jax.jit(
            jax.shard_map(
                lambda v: hvd.alltoall(v, splits=[1] * 8),
                mesh=hvd.global_mesh(),
                in_specs=P(hvd.global_axis_name()),
                out_specs=P(hvd.global_axis_name()),
                check_vma=False,
            )
        )(np.zeros((8, 8), np.float32))


def test_reducescatter_eager(hvd):
    x = np.random.RandomState(3).randn(8, 16, 3).astype(np.float32)
    out = np.asarray(hvd.reducescatter(x, op=hvd.Sum))
    assert out.shape == (8, 2, 3)
    total = x.sum(axis=0)
    for r in range(8):
        np.testing.assert_allclose(out[r], total[2 * r : 2 * r + 2], rtol=1e-5)


def test_reducescatter_average(hvd):
    x = np.ones((8, 8), dtype=np.float32)
    out = np.asarray(hvd.reducescatter(x, op=hvd.Average))
    np.testing.assert_allclose(out, np.ones((8, 1)))


def test_grouped_allreduce_eager(hvd):
    xs = [
        np.random.RandomState(i).randn(8, 3).astype(np.float32) for i in range(3)
    ]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum)
    for x, out in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(out), np.tile(x.sum(0), (8, 1)), rtol=1e-5)


def test_grouped_allgather_eager(hvd):
    n = hvd.size()
    xs = [
        np.arange(n * 2, dtype=np.float32).reshape(n, 2) * (i + 1)
        for i in range(2)
    ]
    outs = hvd.grouped_allgather(xs)
    for x, out in zip(xs, outs):
        out = np.asarray(out)
        # stacked-rank convention: every rank's row holds the concat
        assert out.shape == (n, n * 2), out.shape
        for r in range(n):
            np.testing.assert_array_equal(out[r], x.reshape(-1))


def test_barrier(hvd):
    hvd.barrier()  # must simply not deadlock/throw


# -- process-set scoped collectives ----------------------------------------


def test_allreduce_process_set(hvd):
    ps = hvd.add_process_set([1, 3, 5, 7])
    try:
        x = np.random.RandomState(4).randn(4, 6).astype(np.float32)
        out = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps))
        np.testing.assert_allclose(out, np.tile(x.sum(0), (4, 1)), rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def test_broadcast_process_set_global_root_rank(hvd):
    # root_rank is a GLOBAL rank (reference semantics): 4 is row 1 of the set.
    ps = hvd.add_process_set([0, 4])
    try:
        x = np.stack([np.zeros(3), np.ones(3)]).astype(np.float32)
        out = np.asarray(hvd.broadcast(x, root_rank=4, process_set=ps))
        np.testing.assert_allclose(out, np.ones((2, 3)))
        with pytest.raises(ValueError, match="not a member"):
            hvd.broadcast(x, root_rank=1, process_set=ps)
    finally:
        hvd.remove_process_set(ps)


def test_grouped_allreduce_adasum_not_fused(hvd):
    """Adasum grouped results must equal per-tensor Adasum (no bucket
    coupling of the projection factors)."""
    xs = [
        np.random.RandomState(i).randn(8, 3).astype(np.float32) for i in range(2)
    ]
    grouped = hvd.grouped_allreduce(xs, op=hvd.Adasum)
    single = [hvd.allreduce(x, op=hvd.Adasum) for x in xs]
    for g, s in zip(grouped, single):
        np.testing.assert_allclose(np.asarray(g), np.asarray(s), rtol=1e-6)


# -- traced regime: ops inside a user shard_map ------------------------------


def _traced(hvd, fn, in_specs, out_specs, *args):
    mesh = hvd.global_mesh()
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    )(*args)


def test_allreduce_traced(hvd):
    x = np.arange(8.0, dtype=np.float32)

    def step(v):
        return hvd.allreduce(v, op=hvd.Sum)

    out = _traced(hvd, step, P("hvd"), P("hvd"), x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_allreduce_traced_average(hvd):
    x = np.arange(8.0, dtype=np.float32)

    def step(v):
        return hvd.allreduce(v)

    out = _traced(hvd, step, P("hvd"), P("hvd"), x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


def test_broadcast_traced(hvd):
    x = np.arange(8.0, dtype=np.float32)

    def step(v):
        return hvd.broadcast(v, root_rank=5)

    out = _traced(hvd, step, P("hvd"), P("hvd"), x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 5.0))


def test_allgather_traced(hvd):
    x = np.arange(16.0, dtype=np.float32).reshape(8, 2)

    def step(v):
        return hvd.allgather(v)

    out = _traced(hvd, step, P("hvd"), P(None), x)
    np.testing.assert_array_equal(np.asarray(out), x)


def test_grouped_allreduce_traced_fusion(hvd):
    """Grouped allreduce inside jit must fuse into few psums yet match
    per-tensor results."""
    xs = [np.random.RandomState(i).randn(8, 4).astype(np.float32) for i in range(4)]

    def step(*vs):
        return tuple(hvd.grouped_allreduce(list(vs), op=hvd.Sum))

    outs = _traced(hvd, step, P("hvd"), P("hvd"), *xs)
    for x, out in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(out), np.tile(x.sum(0), (8, 1)), rtol=1e-5)


def test_adasum_identical_grads_idempotent(hvd):
    """Adasum of N identical vectors returns that vector (projection rule)."""
    base = np.random.RandomState(7).randn(4).astype(np.float32)
    x = np.tile(base, (8, 1))
    out = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    np.testing.assert_allclose(out, x, rtol=1e-5)


def test_adasum_orthogonal_grads_sum(hvd):
    """Orthogonal gradients pass through Adasum as a plain sum."""
    x = np.zeros((8, 8), dtype=np.float32)
    for r in range(8):
        x[r, r] = float(r + 1)
    out = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    expected = np.tile(np.arange(1.0, 9.0, dtype=np.float32), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-5)


# -- executable cache (the response-cache analog) ----------------------------


def test_executable_cache_hits(hvd):
    from horovod_tpu.ops.executable_cache import global_cache

    cache = global_cache()
    x = np.random.RandomState(5).randn(8, 7).astype(np.float32)
    hvd.allreduce(x, op=hvd.Sum)
    misses = cache.misses
    hits = cache.hits
    hvd.allreduce(x + 1, op=hvd.Sum)  # same signature -> hit
    assert cache.misses == misses
    assert cache.hits == hits + 1


class TestRaggedHelpers:
    """Pure-numpy ragged-chunk helpers shared by every uneven-exchange
    substrate (alltoall_v, grouped_allgather_v, stacked splits path)."""

    def test_pad_and_compact_chunks_roundtrip(self):
        from horovod_tpu.runtime import compact_chunks, pad_chunks

        x = np.arange(12, dtype=np.float32).reshape(6, 2)
        splits = [1, 3, 2]
        padded = pad_chunks(x, splits, 3)
        assert padded.shape == (9, 2)
        np.testing.assert_array_equal(padded[0], x[0])      # chunk 0
        np.testing.assert_array_equal(padded[3:6], x[1:4])  # chunk 1
        np.testing.assert_array_equal(padded[1:3], 0.0)     # chunk 0 pad
        back = compact_chunks(padded, splits, 3)
        np.testing.assert_array_equal(back, x)

    def test_pad_rows_no_copy_when_exact(self):
        from horovod_tpu.runtime import pad_rows

        x = np.ones((4, 3), np.float32)
        assert pad_rows(x, 4) is x  # uniform case: zero-copy
        padded = pad_rows(x, 6)
        assert padded.shape == (6, 3)
        np.testing.assert_array_equal(padded[4:], 0.0)

    def test_compact_ranks(self):
        from horovod_tpu.runtime import compact_ranks

        g = np.zeros((2, 3, 1), np.float32)
        g[0, :2] = 1.0
        g[1, :1] = 2.0
        out = compact_ranks(g, [2, 1])
        np.testing.assert_array_equal(out, [[1.0], [1.0], [2.0]])

    def test_empty_contributions_everywhere(self):
        from horovod_tpu.runtime import compact_ranks, pad_rows

        x = np.zeros((0, 2), np.float32)
        padded = pad_rows(x, 1)  # the all-empty wire slot
        assert padded.shape == (1, 2)
        out = compact_ranks(np.zeros((3, 1, 2), np.float32), [0, 0, 0])
        assert out.shape == (0, 2)
