"""The bench robustness contract (VERDICT r3 #1): incremental cumulative
emission, transient-error retry, and the driver-facing record keys. These
units protect the machinery that made BENCH_r04 green — a regression here
silently reverts to the all-or-nothing bench that lost round 3's numbers.
"""

import io
import json
import sys

import bench


class TestEmitter:
    def test_every_line_is_the_full_cumulative_record(self, capsys):
        e = bench._Emitter()
        e.update(value=1.0, mfu=0.3)
        e.update(vs_baseline=0.99)
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 2
        first, last = (json.loads(line) for line in out)
        # Driver contract keys present from the very first line.
        for k in ("metric", "value", "unit", "vs_baseline"):
            assert k in first
        # The LAST line carries everything measured so far.
        assert last["value"] == 1.0 and last["mfu"] == 0.3
        assert last["vs_baseline"] == 0.99

    def test_last_line_survives_later_failure(self, capsys):
        e = bench._Emitter()
        e.update(value=2724.07, mfu=0.339)
        # a later section failing emits nothing — the last complete line
        # still holds the headline row.
        out = capsys.readouterr().out.strip().splitlines()
        rec = json.loads(out[-1])
        assert rec["value"] == 2724.07


class TestRetry:
    def test_transient_error_retries_once(self, monkeypatch):
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError(
                    "INTERNAL: http://x/remote_compile: read body: "
                    "response body closed before all bytes were read")
            return "ok"

        errors = []
        assert bench._with_retry("s", flaky, errors) == "ok"
        assert len(calls) == 2 and not errors

    def test_permanent_error_records_and_returns_none(self):
        errors = []
        out = bench._with_retry(
            "s", lambda: (_ for _ in ()).throw(ValueError("shape")), errors)
        assert out is None
        assert len(errors) == 1 and "shape" in errors[0]

    def test_no_retry_in_multi_controller_mode(self, monkeypatch):
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        calls = []

        def flaky():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: socket closed")

        errors = []
        assert bench._with_retry("s", flaky, errors,
                                 allow_retry=False) is None
        assert len(calls) == 1  # a retrying rank would desert its peers

    def test_transient_classification(self):
        assert bench._is_transient(RuntimeError("read body: closed"))
        assert bench._is_transient(ConnectionError("Connection reset"))
        assert not bench._is_transient(ValueError("bad shape"))
