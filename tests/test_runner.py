"""Launcher tests (parity model: the reference's ``test/single/test_run.py``
— horovodrun arg parsing, hosts/slots parsing, env building — plus KV-server
and local-launch integration)."""

import os
import stat
import sys
import textwrap

import pytest

from horovod_tpu.runner import (
    KVClient,
    RendezvousServer,
    get_host_assignments,
    parse_hostfile,
    parse_hosts,
)
from horovod_tpu.runner.hosts import HostParseError, total_slots
from horovod_tpu.runner.launch import (
    args_to_env,
    parse_args,
    run_static,
    settings_from_args,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestHosts:
    def test_parse_hosts(self):
        hosts = parse_hosts("h1:4,h2:4,h3")
        assert [(h.hostname, h.slots) for h in hosts] == [
            ("h1", 4), ("h2", 4), ("h3", 1)
        ]

    def test_parse_hosts_errors(self):
        with pytest.raises(HostParseError):
            parse_hosts("h1:0")
        with pytest.raises(HostParseError):
            parse_hosts("h1:4,h1:2")
        with pytest.raises(HostParseError):
            parse_hosts("")
        with pytest.raises(HostParseError):
            parse_hosts("bad host:2")

    def test_parse_hostfile(self, tmp_path):
        f = tmp_path / "hostfile"
        f.write_text(
            textwrap.dedent(
                """
                # comment
                tpu-vm-0 slots=4
                tpu-vm-1:4
                tpu-vm-2
                """
            )
        )
        hosts = parse_hostfile(str(f))
        assert [(h.hostname, h.slots) for h in hosts] == [
            ("tpu-vm-0", 4), ("tpu-vm-1", 4), ("tpu-vm-2", 1)
        ]

    def test_assignments(self):
        hosts = parse_hosts("h1:4,h2:4,h3:4")
        a = get_host_assignments(hosts, np=2)
        assert len(a) == 2
        assert a[0].hostname == "h1" and a[0].rank == 0
        assert a[1].hostname == "h2" and a[1].rank == 1
        assert all(x.size == 2 and x.cross_size == 2 for x in a)
        assert a[1].first_device_rank == 4
        assert total_slots(a) == 8

    def test_assignments_np_exceeds_hosts(self):
        with pytest.raises(HostParseError):
            get_host_assignments(parse_hosts("h1:4"), np=2)


class TestArgs:
    def test_flags_to_env(self):
        args = parse_args(
            [
                "-np", "2", "--cpu-mode",
                "--fusion-threshold-mb", "32",
                "--cycle-time-ms", "2.5",
                "--timeline-filename", "/tmp/tl.json",
                "--autotune",
                "--hierarchical-allreduce",
                "--log-level", "debug",
                "python", "train.py",
            ]
        )
        env = args_to_env(args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_CYCLE_TIME"] == "2.5"
        assert env["HOROVOD_TIMELINE"] == "/tmp/tl.json"
        assert env["HOROVOD_AUTOTUNE"] == "1"
        assert env["HOROVOD_HIERARCHICAL_ALLREDUCE"] == "1"
        assert env["HOROVOD_LOG_LEVEL"] == "debug"

    def test_settings_local_requires_cpu_mode(self):
        args = parse_args(["-np", "2", "python", "t.py"])
        with pytest.raises(SystemExit):
            settings_from_args(args)

    def test_settings_cpu_mode(self):
        args = parse_args(["-np", "2", "--cpu-mode", "python", "t.py"])
        s = settings_from_args(args)
        assert s.num_proc == 2 and len(s.hosts) == 2 and s.cpu_mode
        assert s.command[0] == "python"

    def test_settings_elastic(self):
        args = parse_args(
            ["--min-np", "1", "--max-np", "3",
             "--host-discovery-script", "./d.sh", "python", "t.py"]
        )
        s = settings_from_args(args)
        assert s.elastic and s.min_np == 1 and s.max_np == 3

    def test_py_command_gets_interpreter(self):
        args = parse_args(["-np", "1", "train.py", "--epochs", "3"])
        s = settings_from_args(args)
        assert s.command == [sys.executable, "train.py", "--epochs", "3"]


class TestSchedulerDetection:
    """LSF/Slurm allocation parsing (parity: horovod/runner/util/lsf.py
    auto-detection; Slurm handled natively instead of via mpirun)."""

    def test_lsf_mcpu_hosts(self):
        from horovod_tpu.runner.schedulers import in_lsf, lsf_hosts

        env = {"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "batch1 1 n1 4 n2 4"}
        assert in_lsf(env)
        hosts = lsf_hosts(env)
        assert [(h.hostname, h.slots) for h in hosts] == [
            ("batch1", 1), ("n1", 4), ("n2", 4)]

    def test_lsf_hosts_repetition(self):
        from horovod_tpu.runner.schedulers import lsf_hosts

        env = {"LSB_JOBID": "1", "LSB_HOSTS": "n1 n1 n2"}
        assert [(h.hostname, h.slots) for h in lsf_hosts(env)] == [
            ("n1", 2), ("n2", 1)]

    def test_lsf_malformed(self):
        from horovod_tpu.runner.hosts import HostParseError
        from horovod_tpu.runner.schedulers import lsf_hosts

        with pytest.raises(HostParseError):
            lsf_hosts({"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "n1 4 n2"})
        with pytest.raises(HostParseError):
            lsf_hosts({"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "n1 zero"})

    def test_slurm_nodelist_expansion(self):
        from horovod_tpu.runner.schedulers import expand_nodelist

        assert expand_nodelist("tpu[001-003,007],login1") == [
            "tpu001", "tpu002", "tpu003", "tpu007", "login1"]
        assert expand_nodelist("a,b") == ["a", "b"]
        assert expand_nodelist("n[9-11]") == ["n9", "n10", "n11"]

    def test_slurm_hosts_with_tasks_per_node(self):
        from horovod_tpu.runner.schedulers import in_slurm, slurm_hosts

        env = {
            "SLURM_JOB_ID": "7",
            "SLURM_JOB_NODELIST": "n[1-4]",
            "SLURM_TASKS_PER_NODE": "2(x3),1",
        }
        assert in_slurm(env)
        assert [(h.hostname, h.slots) for h in slurm_hosts(env)] == [
            ("n1", 2), ("n2", 2), ("n3", 2), ("n4", 1)]

    def test_launcher_uses_allocation_when_no_hosts_flag(self, monkeypatch):
        monkeypatch.setenv("LSB_JOBID", "1")
        monkeypatch.setenv("LSB_MCPU_HOSTS", "n1 1 n2 1 n3 1")
        args = parse_args(["-np", "3", "python", "t.py"])
        s = settings_from_args(args)
        assert [h.hostname for h in s.hosts] == ["n1", "n2", "n3"]
        assert s.num_proc == 3

    def test_explicit_hosts_beat_allocation(self, monkeypatch):
        # even a MALFORMED allocation env must not break explicit -H
        monkeypatch.setenv("LSB_JOBID", "1")
        monkeypatch.setenv("LSB_MCPU_HOSTS", "n1 4 n2")
        args = parse_args(["-np", "1", "-H", "other:1", "python", "t.py"])
        s = settings_from_args(args)
        assert [h.hostname for h in s.hosts] == ["other"]

    def test_cpu_mode_beats_allocation(self, monkeypatch):
        # dev-mode fan-out keeps working inside a 1-node allocation
        monkeypatch.setenv("SLURM_JOB_ID", "5")
        monkeypatch.setenv("SLURM_JOB_NODELIST", "n1")
        args = parse_args(["-np", "4", "--cpu-mode", "python", "t.py"])
        s = settings_from_args(args)
        assert s.num_proc == 4 and len(s.hosts) == 4
        assert all(h.hostname == "localhost" for h in s.hosts)


class TestKVServer:
    def test_put_get_roundtrip(self):
        server = RendezvousServer()
        port = server.start()
        try:
            c = KVClient("127.0.0.1", port)
            assert c.get("s", "missing") is None
            c.put("s", "k1", b"v1")
            c.put("s", "k2", b"v2")
            assert c.get("s", "k1") == b"v1"
            assert sorted(c.keys("s")) == ["k1", "k2"]
            assert c.world_version() == 0
            assert server.reset() == 1
            assert c.world_version() == 1
            assert c.get("s", "k1") is None  # reset clears scopes
            c.put("s2", "k", b"x")
            c.delete_scope("s2")
            assert c.get("s2", "k") is None
        finally:
            server.stop()


def _worker_script(tmp_path, body: str) -> str:
    path = tmp_path / "worker.py"
    path.write_text(
        "import os, sys, time\n"
        f"sys.path.insert(0, {str(REPO_ROOT)!r})\n" + textwrap.dedent(body)
    )
    return str(path)


class TestStaticLaunch:
    def test_two_local_workers_env_and_prefixes(self, tmp_path):
        script = _worker_script(
            tmp_path,
            """
            print("rank=%s size=%s cross=%s/%s pid=%s np=%s" % (
                os.environ["HOROVOD_RANK"], os.environ["HOROVOD_SIZE"],
                os.environ["HOROVOD_CROSS_RANK"], os.environ["HOROVOD_CROSS_SIZE"],
                os.environ["HOROVOD_PROCESS_ID"], os.environ["HOROVOD_NUM_PROCESSES"]))
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0
        assert "[0] rank=0 size=2 cross=0/2 pid=0 np=2" in lines
        assert "[1] rank=1 size=2 cross=1/2 pid=1 np=2" in lines

    def test_failure_propagates(self, tmp_path):
        script = _worker_script(
            tmp_path,
            """
            if os.environ["HOROVOD_RANK"] == "1":
                sys.exit(7)
            time.sleep(30)  # rank 0 would hang; launcher must kill it
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        rc = run_static(settings, sink=lambda s: None)
        assert rc == 7

    def test_check_build(self, capsys):
        from horovod_tpu.runner.launch import run_commandline

        assert run_commandline(["--check-build"]) == 0
        out = capsys.readouterr().out
        assert "XLA:TPU" in out and "elastic" in out

    @pytest.mark.slow
    def test_e2e_multiprocess_allreduce(
            self, tmp_path, require_multiprocess_cpu_collectives):
        """Full stack: hvdrun → 2 processes → jax.distributed world →
        cross-process eager allreduce (the launcher analog of the
        reference's `horovodrun -np 2 python -c "hvd.allreduce(...)"`)."""
        script = _worker_script(
            tmp_path,
            """
            # Workers form their own 2-process world. jax may already be
            # imported (sitecustomize), so env alone is too late: use
            # config.update like tests/conftest.py does.
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(2)
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            assert hvd.size() == 4, hvd.size()  # 2 procs x 2 virtual devices
            assert hvd.process_count() == 2
            # Stacked-rank eager allreduce across the whole world; each
            # process reads its addressable rows via to_local. The stacked
            # form takes a jax.Array (process-identical global data) —
            # numpy would mean the per-process idiom.
            import jax.numpy as jnp
            x = jnp.asarray(
                np.arange(4, dtype=np.float32).reshape(4, 1) + 1)
            out = hvd.to_local(hvd.allreduce(x, op=hvd.Sum))
            assert np.allclose(out, 10.0), out
            print("e2e rank%s ok sum=%s" % (hvd.process_rank(), out[0, 0]))
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        # Each process fabricates 2 virtual devices (the worker script sets
        # XLA_FLAGS itself; slots stay 1 in the assignment).
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("e2e rank0 ok sum=10.0" in l for l in lines), lines
        assert any("e2e rank1 ok sum=10.0" in l for l in lines), lines


class TestRemoteWorkerTermination:
    """Regression (round-1 advisor, VERDICT r2 item 3c): terminate_worker
    used to kill only the local ssh client; the remote process tree
    survived. Now launch records a remote pidfile (+ ssh -tt for pty-HUP)
    and terminate signals the remote process group explicitly."""

    def _launch_fake_remote(self, monkeypatch):
        from horovod_tpu.runner import exec_utils
        from horovod_tpu.runner.hosts import HostInfo, get_host_assignments

        captured = {}
        real_popen = exec_utils.subprocess.Popen

        def fake_popen(cmd, **kw):
            captured["cmd"] = cmd
            # Stand-in process so pump/poll/terminate paths work.
            return real_popen(
                [sys.executable, "-c", "import time; time.sleep(30)"],
                stdout=exec_utils.subprocess.PIPE,
                stderr=exec_utils.subprocess.STDOUT,
                start_new_session=True,
            )

        monkeypatch.setattr(exec_utils.subprocess, "Popen", fake_popen)
        a = get_host_assignments([HostInfo("remote-node-1", 1)])[0]
        w = exec_utils.launch_worker(
            a, ["python", "train.py"], {"HOROVOD_RANK": "0"})
        return exec_utils, captured, w

    def test_remote_launch_uses_tt_and_pidfile(self, monkeypatch):
        exec_utils, captured, w = self._launch_fake_remote(monkeypatch)
        try:
            cmd = captured["cmd"]
            assert cmd[0] == "ssh" and "-tt" in cmd
            remote_cmd = cmd[-1]
            # Pidfile recorded in a per-user 0700 dir, cleaned by EXIT trap.
            assert "umask 077" in remote_cmd
            assert "echo $$ >" in remote_cmd
            assert "trap 'rm -f" in remote_cmd
            assert w.remote_host == "remote-node-1"
            assert w.kill_marker and w.kill_marker in remote_cmd
        finally:
            w.popen.kill()

    def test_terminate_issues_remote_group_kill(self, monkeypatch):
        exec_utils, captured, w = self._launch_fake_remote(monkeypatch)
        kills = []
        monkeypatch.setattr(
            exec_utils.subprocess, "run",
            lambda cmd, **kw: kills.append(cmd))
        exec_utils.terminate_worker(w, grace_s=0.2)
        assert kills, "terminate_worker never ssh'd to the remote host"
        kill_cmd = kills[0]
        assert kill_cmd[0] == "ssh" and kill_cmd[-2] == "remote-node-1"
        assert f"{w.kill_marker}.pid" in kill_cmd[-1]
        assert "kill -TERM -- -$p" in kill_cmd[-1]
        assert w.popen.poll() is not None  # local ssh stand-in died too

    def test_local_worker_untouched_by_remote_path(self):
        from horovod_tpu.runner import exec_utils
        from horovod_tpu.runner.hosts import HostInfo, get_host_assignments

        a = get_host_assignments([HostInfo("localhost", 1)])[0]
        w = exec_utils.launch_worker(
            a, [sys.executable, "-c", "import time; time.sleep(30)"],
            dict(os.environ))
        try:
            assert w.remote_host is None and w.kill_marker is None
            exec_utils.terminate_worker(w, grace_s=0.2)
            assert w.popen.poll() is not None
        finally:
            if w.popen.poll() is None:
                w.popen.kill()


class TestNativePortWiring:
    """VERDICT r2 item 2: the launcher must make the native C++ runtime
    reachable with NO hand-set env — build_worker_env carries
    HOROVOD_NATIVE_PORT, so hvd.join() and host_hierarchical_allreduce
    come up under a plain `hvdrun -np 2 --cpu-mode`."""

    def test_build_worker_env_sets_native_port(self):
        from horovod_tpu.runner.exec_utils import build_worker_env
        from horovod_tpu.runner.hosts import HostInfo, get_host_assignments

        a = get_host_assignments([HostInfo("localhost", 1)])[0]
        env = build_worker_env(
            a, base_env={}, rendezvous_addr="127.0.0.1",
            rendezvous_port=1234, coordinator_addr="127.0.0.1",
            coordinator_port=5678, native_port=4321)
        assert env["HOROVOD_NATIVE_PORT"] == "4321"

    @pytest.mark.slow
    def test_e2e_join_and_host_hierarchical(self, tmp_path):
        """hvdrun -np 2 --cpu-mode; workers use the native runtime purely
        from the launcher's env: host_hierarchical_allreduce then an
        uneven-data hvd.join()."""
        script = _worker_script(
            tmp_path,
            """
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(2)
            import numpy as np
            import horovod_tpu as hvd
            from horovod_tpu.parallel.hierarchical import (
                host_hierarchical_allreduce,
            )

            assert "HOROVOD_NATIVE_PORT" in os.environ  # launcher-provided
            hvd.init()
            pid = hvd.process_rank()
            # Host hierarchical allreduce: local XLA leg + native cross leg.
            local = np.full((2, 4), float(pid + 1), np.float32)
            out = host_hierarchical_allreduce(local, name="e2e", op="sum")
            # Global logical world = 2 procs x 2 local shards:
            # sum over shards = 2*(1) + 2*(2) = 6 per element.
            assert np.allclose(out, 6.0), out
            # Uneven data: rank 0 joins after 1 extra allreduce by rank 1.
            from horovod_tpu.parallel.hierarchical import (
                _default_native_world,
            )
            w = _default_native_world()
            if pid == 1:
                r = w.allreduce(np.ones(3, np.float32), name="extra",
                                op="average")
                # rank 0 is joined: average over contributing ranks only.
                assert np.allclose(r, 1.0), r
            last = hvd.join()
            assert last in (0, 1)
            print("join-e2e rank%s ok last=%s" % (pid, last))
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("join-e2e rank0 ok" in l for l in lines), lines
        assert any("join-e2e rank1 ok" in l for l in lines), lines


class TestPerProcessEagerIdiom:
    """VERDICT r2 item 7: the reference's per-process scripting idiom —
    plain `hvd.allreduce(np_array)` on each process's OWN tensor — must
    work verbatim in a multi-controller world (routed through the native
    runtime host data plane)."""

    @pytest.mark.slow
    def test_e2e_per_process_allreduce(self, tmp_path):
        script = _worker_script(
            tmp_path,
            """
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(2)
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            pid = hvd.process_rank()
            # Reference idiom: each process reduces ITS tensor. No stacking
            # axis -> native host path (device world is 4; shape is (3,)).
            t = np.full(3, float(pid + 1), np.float32)
            out = hvd.allreduce(t, op=hvd.Sum, name="mine")
            assert np.allclose(out, 3.0), out   # 1 + 2
            avg = hvd.allreduce(t, name="avg")  # default Average
            assert np.allclose(avg, 1.5), avg
            # allgather concatenates process tensors along dim 0 — with
            # per-rank DIFFERENT sizes (the reference's ragged contract).
            rows = 2 + pid  # rank 0: 2 rows, rank 1: 3 rows
            g = hvd.allgather(np.full((rows, 2), float(pid), np.float32))
            assert g.shape == (5, 2), g.shape
            assert np.allclose(g[:2], 0.0) and np.allclose(g[2:], 1.0), g
            # broadcast: process 1's value everywhere.
            b = hvd.broadcast(t, root_rank=1)
            assert np.allclose(b, 2.0), b
            # grouped: one fused native collective.
            r1, r2 = hvd.grouped_allreduce(
                [np.ones(4, np.float32) * (pid + 1),
                 np.ones(2, np.float32) * (pid + 1)], op=hvd.Sum)
            assert np.allclose(r1, 3.0) and np.allclose(r2, 3.0)
            # allgather_object: per-process objects, expanded per device
            # rank (2 procs x 2 devices -> 4 entries).
            objs = hvd.allgather_object({"pid": pid})
            assert [o["pid"] for o in objs] == [0, 0, 1, 1], objs
            # grouped_allgather: one ATOMIC native group (uniform dim-0).
            ga1, ga2 = hvd.grouped_allgather(
                [np.full((2, 1), float(pid), np.float32),
                 np.full((1,), float(10 + pid), np.float32)])
            assert ga1.shape == (4, 1), ga1.shape
            assert np.allclose(ga1[:2], 0.0) and np.allclose(ga1[2:], 1.0)
            assert np.allclose(ga2, [10.0, 11.0]), ga2
            hvd.barrier()
            print("perproc rank%s ok" % pid)
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("perproc rank0 ok" in l for l in lines), lines
        assert any("perproc rank1 ok" in l for l in lines), lines


class TestConfigFile:
    def test_yaml_defaults_cli_wins(self, tmp_path):
        cfg = tmp_path / "hvd.yml"
        cfg.write_text(
            "cpu-mode: true\n"
            "fusion-threshold-mb: 16\n"
            "log-level: debug\n"
            "num_proc: 4\n"
        )
        # CLI -np 2 beats the file's num_proc; file fills the rest.
        args = parse_args(["-np", "2", "--config-file", str(cfg),
                           "python", "t.py"])
        assert args.num_proc == 2
        assert args.cpu_mode is True
        assert args.fusion_threshold_mb == 16
        assert args.log_level == "debug"
        env = args_to_env(args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(16 * 1024 * 1024)

    def test_unknown_key_rejected(self, tmp_path):
        cfg = tmp_path / "hvd.yml"
        cfg.write_text("bogus-flag: 1\n")
        with pytest.raises(SystemExit, match="unknown option"):
            parse_args(["--config-file", str(cfg), "python", "t.py"])


class TestPerProcessSubsetCollectives:
    """Python process sets map onto native-runtime sets in one-device-per-
    process worlds: subset eager collectives work verbatim across
    processes (two disjoint sets reducing concurrently)."""

    @pytest.mark.slow
    def test_e2e_subset_eager(self, tmp_path):
        script = _worker_script(
            tmp_path,
            """
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            pid = hvd.process_rank()
            assert hvd.size() == 4 and hvd.process_count() == 4
            evens = hvd.add_process_set([0, 2])
            odds = hvd.add_process_set([1, 3])
            mine = evens if pid % 2 == 0 else odds
            peers = [0, 2] if pid % 2 == 0 else [1, 3]
            t = np.full(3, float(pid + 1), np.float32)
            out = hvd.allreduce(t, op=hvd.Sum, process_set=mine,
                                name=f"sub.{pid % 2}")
            assert np.allclose(out, sum(p + 1 for p in peers)), out
            g = hvd.allgather(np.full((1, 2), float(pid), np.float32),
                              process_set=mine)
            assert np.asarray(g).shape == (2, 2)
            assert np.allclose(np.asarray(g)[:, 0], peers), g
            b = hvd.broadcast(t, root_rank=peers[1], process_set=mine)
            assert np.allclose(b, peers[1] + 1.0), b
            # subset work is uneven across sets: barrier before exit —
            # the first exiting rank's (negotiated) shutdown would reach
            # the other set mid-collective (reference semantics; see
            # docs/process_set.md).
            hvd.barrier()
            print("subset rank%s ok" % pid, flush=True)
            """,
        )
        args = parse_args(["-np", "4", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        for r in range(4):
            assert any(f"subset rank{r} ok" in l for l in lines), lines


class TestElasticTrainStepMultiProcess:
    """make_elastic_train_step's cross-process leg: 2 processes with
    UNEQUAL device counts (1 vs 3) train on different shards; the
    device-count-weighted cross averaging must match the single-process
    oracle on the concatenated data exactly (equal per-process votes
    would be biased)."""

    @pytest.mark.slow
    def test_two_process_matches_oracle(self, tmp_path):
        script = _worker_script(
            tmp_path,
            """
            os.environ["JAX_PLATFORMS"] = "cpu"
            # Elastic regime: no jax.distributed -> each process keeps a
            # LOCAL device mesh; the cross-process leg is the native host
            # plane (what make_elastic_train_step is for).
            os.environ.pop("HOROVOD_COORDINATOR_ADDR", None)
            import jax
            jax.config.update("jax_platforms", "cpu")
            pid = int(os.environ["HOROVOD_PROCESS_ID"])
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(1 if pid == 0 else 3)
            import numpy as np
            import jax.numpy as jnp
            import optax
            import horovod_tpu as hvd
            from horovod_tpu.parallel import data_parallel as dp

            hvd.init()
            rng = np.random.RandomState(0)  # same data everywhere
            X = rng.randn(8, 3).astype(np.float32)
            Y = rng.randn(8, 2).astype(np.float32)
            w0 = jnp.asarray(rng.randn(3, 2).astype(np.float32))

            def loss_fn(params, batch):
                bx, by = batch
                return jnp.mean((bx @ params - by) ** 2)

            # Proc 0: 1 device x 2 rows; proc 1: 3 devices x 2 rows each —
            # every DEVICE sees 2 rows, so the weighted mean over devices
            # equals the full-batch mean over all 8 rows.
            mine = ((X[:2], Y[:2]) if pid == 0
                    else (X[2:8], Y[2:8]))
            opt = optax.sgd(0.1)
            step = dp.make_elastic_train_step(loss_fn, opt)
            params, opt_state = w0, opt.init(w0)
            for _ in range(3):
                params, opt_state, loss = step(
                    params, opt_state, dp.shard_batch(mine))

            # Oracle: full-batch gradient descent on the SAME math.
            ow, oo = w0, optax.sgd(0.1).init(w0)
            oopt = optax.sgd(0.1)
            for _ in range(3):
                g = jax.grad(lambda p: jnp.mean((X @ p - Y) ** 2))(ow)
                up, oo = oopt.update(g, oo, ow)
                ow = optax.apply_updates(ow, up)
            assert np.allclose(np.asarray(params), np.asarray(ow),
                               rtol=1e-4, atol=1e-5), (params, ow)
            print("elastic-step rank%d ok loss=%.5f" % (pid, float(loss)),
                  flush=True)
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("elastic-step rank0 ok" in l for l in lines), lines
        assert any("elastic-step rank1 ok" in l for l in lines), lines
