"""TF/Keras API surface tests (BASELINE config #3 parity layer).

Single-process: identity short-circuits + wrapper mechanics. Multi-process
(slow): hvdrun -np 2 --cpu-mode e2e — DistributedGradientTape averages real
gradients across processes via the native runtime, broadcast_variables
synchronizes weights, Keras optimizer wrapper trains in lockstep."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.keras as hvd_keras  # noqa: E402
import horovod_tpu.tensorflow as hvd_tf  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestSingleProcess:
    def test_world_facts_and_identity_ops(self):
        hvd_tf.init()
        assert hvd_tf.size() >= 1 and hvd_tf.rank() >= 0
        t = tf.constant([1.0, 2.0])
        out = hvd_tf.allreduce(t)
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0])
        g = hvd_tf.allgather(t)
        np.testing.assert_allclose(g.numpy(), [1.0, 2.0])
        b = hvd_tf.broadcast(t, root_rank=0)
        np.testing.assert_allclose(b.numpy(), [1.0, 2.0])

    def test_allreduce_under_tf_function(self):
        hvd_tf.init()

        @tf.function
        def step(x):
            return hvd_tf.allreduce(x, op=hvd_tf.Sum)

        out = step(tf.constant([2.0, 4.0]))
        np.testing.assert_allclose(out.numpy(), [2.0, 4.0])

    def test_distributed_gradient_tape_passthrough(self):
        v = tf.Variable([2.0, 3.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(v * v)
        dtape = hvd_tf.DistributedGradientTape(tape)
        grads = dtape.gradient(loss, [v])
        np.testing.assert_allclose(grads[0].numpy(), [4.0, 6.0])

    def test_keras_optimizer_wrapper_single_process(self):
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.5))
        assert "SGD" in type(opt).__name__
        v = tf.Variable([2.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(v * v)
        grads = tape.gradient(loss, [v])
        opt.apply_gradients(zip(grads, [v]))
        np.testing.assert_allclose(v.numpy(), [0.0])  # 2 - 0.5*4

    def test_tensorflow_keras_module_path_and_optimizer_entry(self):
        """Reference import parity: `import horovod.tensorflow.keras` and
        TF2 scripts' `hvd.DistributedOptimizer(keras_opt)`."""
        import horovod_tpu.tensorflow.keras as hvdk2

        assert hvdk2.DistributedOptimizer is hvd_keras.DistributedOptimizer
        assert hvdk2.callbacks.BroadcastGlobalVariablesCallback \
            is hvd_keras.callbacks.BroadcastGlobalVariablesCallback
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.5))
        assert "SGD" in type(opt).__name__
        with pytest.raises(TypeError, match="keras optimizers"):
            hvd_tf.DistributedOptimizer(object())

    def test_lr_schedule_callback(self):
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(2,))])
        model.compile(optimizer=tf.keras.optimizers.SGD(0.1), loss="mse",
                      run_eagerly=True)
        cb = hvd_keras.callbacks.LearningRateScheduleCallback(
            initial_lr=0.1, multiplier=lambda e: 0.5 ** e, start_epoch=1)
        lrs = []

        class Probe(tf.keras.callbacks.Callback):
            def on_epoch_begin(self, epoch, logs=None):
                lrs.append(float(self.model.optimizer.learning_rate))

        x = np.ones((8, 2), np.float32)
        y = np.ones((8, 1), np.float32)
        model.fit(x, y, epochs=4, batch_size=8, verbose=0,
                  callbacks=[cb, Probe()])
        # epoch 0 untouched (before start_epoch); then 0.1 * 0.5**e
        assert lrs[0] == pytest.approx(0.1)
        assert lrs[1] == pytest.approx(0.05)
        assert lrs[2] == pytest.approx(0.025)

    def test_surface_export_parity(self):
        """Reference export audit: the TF surface carries the basics'
        build-introspection shims; the keras surface re-exports the full
        TF world (upstream horovod.keras does the same)."""
        for n in ("mpi_built", "mpi_enabled", "gloo_built", "gloo_enabled",
                  "nccl_built", "ddl_built", "ccl_built", "cuda_built",
                  "rocm_built", "mpi_threads_supported"):
            assert hasattr(hvd_tf, n), n
        assert not hvd_tf.mpi_built()
        assert hvd_tf.nccl_built()  # XLA/ICI plays NCCL's role
        for n in ("allgather_object", "broadcast_object", "join",
                  "alltoall", "reducescatter", "barrier", "cross_rank",
                  "cross_size", "local_size", "is_homogeneous",
                  "is_initialized", "mpi_built", "start_timeline",
                  "stop_timeline", "remove_process_set"):
            assert hasattr(hvd_keras, n), n

    def test_broadcast_variables_noop_single(self):
        v = tf.Variable([1.0, 2.0])
        hvd_tf.broadcast_variables([v], root_rank=0)
        np.testing.assert_allclose(v.numpy(), [1.0, 2.0])


def _parse_digests(lines, marker: str) -> dict:
    """Collect {rank: digest} from worker stdout lines of the form
    '<marker> rank<N> digest <float>'."""
    digests = {}
    for line in lines:
        if marker + " rank" in line:
            part = line.split(marker + " rank", 1)[1]
            rank, dig = part.split(" digest ")
            digests[int(rank)] = float(dig)
    return digests


def _worker_script(tmp_path, body: str) -> str:
    path = tmp_path / "tf_worker.py"
    path.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {str(REPO_ROOT)!r})\n" + textwrap.dedent(body)
    )
    return str(path)


@pytest.mark.slow
class TestMultiProcess:
    def test_e2e_tape_and_broadcast(self, tmp_path):
        from horovod_tpu.runner.launch import (
            parse_args, run_static, settings_from_args,
        )

        script = _worker_script(
            tmp_path,
            """
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.tensorflow as hvd

            hvd.init()
            r = hvd.rank()
            assert hvd.size() == 2
            # Gradients averaged across processes.
            v = tf.Variable([float(r + 1)] * 3)
            with tf.GradientTape() as tape:
                loss = tf.reduce_sum(v * v)
            tape = hvd.DistributedGradientTape(tape)
            (g,) = tape.gradient(loss, [v])
            # grads: rank0 [2,2,2], rank1 [4,4,4] -> avg [3,3,3]
            assert np.allclose(g.numpy(), 3.0), g.numpy()
            # Second step hits the response cache (same names).
            with tf.GradientTape() as tape2:
                loss2 = tf.reduce_sum(v * 2.0)
            tape2 = hvd.DistributedGradientTape(tape2)
            (g2,) = tape2.gradient(loss2, [v])
            assert np.allclose(g2.numpy(), 2.0), g2.numpy()
            # broadcast_variables: everyone gets rank 0's weights.
            hvd.broadcast_variables([v], root_rank=0)
            assert np.allclose(v.numpy(), 1.0), v.numpy()
            # tf.function (graph) collectives run as py_function host ops.
            @tf.function
            def graph_sum(x):
                return hvd.allreduce(x, op=hvd.Sum, name="graph.ar")
            gsum = graph_sum(tf.constant([float(r + 1)] * 2))
            assert np.allclose(gsum.numpy(), 3.0), gsum.numpy()
            # alltoall: rank r sends [r*10, r*10+1]; rank k receives
            # row k of every rank.
            a2a = hvd.alltoall(
                tf.constant([[10.0 * r + 0], [10.0 * r + 1]]))
            expect = np.array([[0.0 + r], [10.0 + r]])
            assert np.allclose(np.asarray(a2a), expect), a2a
            # alltoall with uneven splits: rank r sends r+1 rows to rank
            # 0 and the rest to rank 1 -> reference (output,
            # received_splits) pair. Eagerly AND inside tf.function (the
            # two-output py_function path with in-graph splits).
            rows = tf.fill((3, 1), float(r))
            sp = tf.constant([r + 1, 2 - r], dtype=tf.int64)
            out_v, recv = hvd.alltoall(rows, splits=sp)
            # rank 0 receives: 1 row of 0.0, 2 rows of 1.0; rank 1: 2
            # rows of 0.0, 1 row of 1.0
            expect_v = [[0.0], [1.0], [1.0]] if r == 0 else \
                [[0.0], [0.0], [1.0]]
            assert np.allclose(np.asarray(out_v), expect_v), out_v
            assert np.asarray(recv).tolist() == (
                [1, 2] if r == 0 else [2, 1]), recv

            @tf.function
            def graph_a2av(x):
                return hvd.alltoall(
                    x, splits=tf.constant([r + 1, 2 - r], tf.int64),
                    name="g.a2av")
            out_g, recv_g = graph_a2av(rows)
            assert np.allclose(np.asarray(out_g), expect_v), out_g
            assert np.asarray(recv_g).tolist() == (
                [1, 2] if r == 0 else [2, 1]), recv_g

            # reducescatter: reduce then shard dim 0 (default Average,
            # reference parity).
            rs = hvd.reducescatter(
                tf.constant([[1.0 + r, 2.0], [3.0, 4.0]]), op=hvd.Sum)
            # summed: [[3,4],[6,8]]; rank r gets row r
            expect_rs = np.array([[3.0, 4.0], [6.0, 8.0]])[r]
            assert np.allclose(np.asarray(rs), expect_rs), rs
            rs_avg = hvd.reducescatter(
                tf.constant([[1.0 + r, 2.0], [3.0, 4.0]]))
            assert np.allclose(np.asarray(rs_avg), expect_rs / 2.0), rs_avg
            # single (non-list) source keeps its structure: one tensor
            # back, not a list of rows.
            with tf.GradientTape() as ts:
                lss = tf.reduce_sum(v * float(r + 1))
            gs = hvd.DistributedGradientTape(ts).gradient(lss, v)
            assert tf.is_tensor(gs) and gs.shape == v.shape, gs
            assert np.allclose(gs.numpy(), 1.5), gs.numpy()
            # fp16-compressed tape: wire is half precision, result comes
            # back f32 and still averages correctly.
            with tf.GradientTape() as t4:
                l4 = tf.reduce_sum(v * float(r + 1))
            t4 = hvd.DistributedGradientTape(
                t4, compression=hvd.Compression.fp16)
            (g4,) = t4.gradient(l4, [v])
            assert g4.dtype == tf.float32
            assert np.allclose(g4.numpy(), 1.5), g4.numpy()
            # sparse gradients: rejected without sparse_as_dense, dense
            # allreduce with it (embedding-style gather).
            emb = tf.Variable(np.full((4, 2), float(r + 1), np.float32))
            with tf.GradientTape() as t5:
                rows = tf.gather(emb, [0, 2])
                l5 = tf.reduce_sum(rows)
            t5w = hvd.DistributedGradientTape(t5)
            try:
                t5w.gradient(l5, [emb])
                raise AssertionError("sparse grad should be rejected")
            except ValueError as e:
                assert "sparse_as_dense" in str(e)
            with tf.GradientTape() as t6:
                rows = tf.gather(emb, [0, 2])
                l6 = tf.reduce_sum(rows * float(r + 1))
            t6w = hvd.DistributedGradientTape(t6, sparse_as_dense=True)
            (g6,) = t6w.gradient(l6, [emb])
            # rank grads: rows 0,2 are r+1 -> avg 1.5; rows 1,3 zero
            g6 = np.asarray(g6)
            assert np.allclose(g6[[0, 2]], 1.5), g6
            assert np.allclose(g6[[1, 3]], 0.0), g6
            # grouped allgather / reducescatter
            ga = hvd.grouped_allgather(
                [tf.constant([[float(r)]]), tf.constant([[float(5 + r)]])],
                name="g.gag")
            assert np.asarray(ga[0]).shape == (2, 1), ga
            assert np.allclose(np.asarray(ga[1]).ravel(), [5.0, 6.0]), ga
            # ragged grouped allgather (reference contract)
            gv = hvd.grouped_allgather(
                [tf.fill((r + 1, 2), float(r))], name="g.gagv")
            assert np.asarray(gv[0]).shape == (3, 2), gv
            assert np.allclose(np.asarray(gv[0])[:1], 0.0), gv
            assert np.allclose(np.asarray(gv[0])[1:], 1.0), gv
            grs = hvd.grouped_reducescatter(
                [tf.constant([[1.0 + r], [3.0 + r]])], op=hvd.Sum,
                name="g.grs")
            # summed [[3],[7]]; rank r gets row r
            assert np.allclose(np.asarray(grs[0]), [[3.0, 7.0][r]]), grs

            # object collectives (reference horovod/tensorflow/functions)
            bo = hvd.broadcast_object({"cfg": r * 10}, root_rank=1)
            assert bo == {"cfg": 10}, bo
            ao = hvd.allgather_object(("r", r))
            assert ao == [("r", 0), ("r", 1)], ao
            # Keras optimizer wrapper trains in lockstep.
            import horovod_tpu.keras as hvdk
            opt = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=0.1))
            w = tf.Variable([float(r)])
            with tf.GradientTape() as t3:
                l3 = tf.reduce_sum(w * 3.0)
            grads = t3.gradient(l3, [w])
            opt.apply_gradients(zip(grads, [w]))
            # grad = 3 on both ranks -> averaged 3 -> w -= 0.3
            assert np.allclose(w.numpy(), float(r) - 0.3), w.numpy()
            # keras wrapper with bf16 wire compression: same averaged step
            opt2 = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=0.1),
                compression=hvdk.Compression.bf16)
            w2 = tf.Variable([float(r)])
            with tf.GradientTape() as t7:
                l7 = tf.reduce_sum(w2 * 3.0)
            opt2.apply_gradients(zip(t7.gradient(l7, [w2]), [w2]))
            assert np.allclose(w2.numpy(), float(r) - 0.3, atol=1e-2)
            print("tf-e2e rank%d ok" % r)
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("tf-e2e rank0 ok" in l for l in lines), lines
        assert any("tf-e2e rank1 ok" in l for l in lines), lines

    def test_e2e_process_sets(self, tmp_path):
        """process_set= on the TF surface: two disjoint 2-rank sets
        reduce concurrently in a 4-process world; the tape scopes
        gradient averaging to the set."""
        from horovod_tpu.runner.launch import (
            parse_args, run_static, settings_from_args,
        )

        script = _worker_script(
            tmp_path,
            """
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.tensorflow as hvd

            hvd.init()
            r = hvd.rank()
            assert hvd.size() == 4
            evens = hvd.add_process_set([0, 2])
            odds = hvd.add_process_set([1, 3])
            mine = evens if r % 2 == 0 else odds

            out = hvd.allreduce(tf.constant([float(r)]), op=hvd.Sum,
                                name="tfps.ar", process_set=mine)
            expect = {0: 2.0, 2: 2.0, 1: 4.0, 3: 4.0}[r]
            assert float(out[0]) == expect, (r, out)

            v = tf.Variable([float(r)])
            with tf.GradientTape() as tape:
                loss = tf.reduce_sum(v * float(r + 1))
            tape = hvd.DistributedGradientTape(tape, process_set=mine)
            (g,) = tape.gradient(loss, [v])
            # evens avg(1,3)=2; odds avg(2,4)=3
            expect_g = 2.0 if r % 2 == 0 else 3.0
            assert np.allclose(g.numpy(), expect_g), (r, g.numpy())

            b = hvd.broadcast(tf.constant([float(r + 20)]),
                              0 if r % 2 == 0 else 1,
                              name="tfps.b", process_set=mine)
            assert float(b[0]) == (20.0 if r % 2 == 0 else 21.0), b

            # keras optimizer wrapper scoped to the subset: grads average
            # within the set (evens avg(1,3)=2; odds avg(2,4)=3), lr=1.
            import horovod_tpu.keras as hvdk
            opt = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=1.0),
                process_set=mine)
            w = tf.Variable([0.0])
            with tf.GradientTape() as kt:
                kl = tf.reduce_sum(w * float(r + 1))
            opt.apply_gradients(zip(kt.gradient(kl, [w]), [w]))
            expect_w = -2.0 if r % 2 == 0 else -3.0
            assert np.allclose(w.numpy(), expect_w), (r, w.numpy())

            # subset work is uneven across sets: a global barrier keeps
            # the earliest-finishing rank from shutting the world down
            # under a peer's in-flight subset op (reference usage).
            hvd.barrier()
            print("tfps rank%d ok" % r)
            """,
        )
        args = parse_args(["-np", "4", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        for i in range(4):
            assert any(f"tfps rank{i} ok" in l for l in lines), lines

    def test_keras_state_sync_flows_across_processes(self, tmp_path):
        """TensorFlowKerasState.sync() must really move rank 0's model
        weights, optimizer slots, and extras to other ranks through the
        HOST plane (regression: it previously rode the jax.distributed
        broadcast_object, which silently no-ops in hvdrun workers)."""
        from horovod_tpu.runner.launch import (
            parse_args, run_static, settings_from_args,
        )

        script = _worker_script(
            tmp_path,
            """
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.keras as hvdk
            from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

            hvdk.init()
            r = hvdk.rank()
            tf.random.set_seed(123)  # same init everywhere
            model = tf.keras.Sequential(
                [tf.keras.layers.Dense(1, input_shape=(3,))])
            opt = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(0.1, momentum=0.9))
            state = TensorFlowKerasState(model=model, optimizer=opt,
                                         epoch=0)
            # one real step so momentum slots exist, then DIVERGE rank 1
            x = tf.constant(np.ones((4, 3), np.float32))
            with tf.GradientTape() as t:
                loss = tf.reduce_mean(model(x) ** 2)
            opt.apply_gradients(zip(
                t.gradient(loss, model.trainable_variables),
                model.trainable_variables))
            state.epoch = 7 if r == 0 else 99
            if r == 1:
                model.set_weights(
                    [w * 0 + 5.0 for w in model.get_weights()])
                for v in opt.variables:
                    try:
                        v.assign(tf.ones_like(v) * 9.0)
                    except Exception:
                        pass
            state.sync()
            digest = float(sum(np.abs(w).sum()
                               for w in model.get_weights()))
            slots = float(sum(
                np.abs(np.asarray(v)).sum() for v in opt.variables
                if np.asarray(v).dtype.kind == "f"))
            print("sync rank%d epoch %d digest %.6f slots %.6f"
                  % (r, state.epoch, digest, slots), flush=True)
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        vals = {}
        for line in lines:
            if "sync rank" in line:
                part = line.split("sync rank", 1)[1].split()
                vals[int(part[0])] = (int(part[2]), float(part[4]),
                                      float(part[6]))
        assert set(vals) == {0, 1}, lines
        # rank 1's divergent epoch/weights/slots were overwritten by rank 0's
        assert vals[1][0] == 7, vals
        assert vals[0][1] == pytest.approx(vals[1][1], abs=1e-5), vals
        assert vals[0][2] == pytest.approx(vals[1][2], abs=1e-5), vals

    def test_sync_batch_norm_matches_full_batch(self, tmp_path):
        """Each rank holds half the batch; SyncBatchNormalization's
        training output and gradients must equal stock BatchNormalization
        over the CONCATENATED batch (computed locally as the oracle)."""
        from horovod_tpu.runner.launch import (
            parse_args, run_static, settings_from_args,
        )

        script = _worker_script(
            tmp_path,
            """
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.tensorflow as hvd

            hvd.init()
            r = hvd.rank()
            rng = np.random.RandomState(7)
            full = rng.randn(8, 3).astype(np.float32) * 2.0 + 1.0
            mine = full[r * 4:(r + 1) * 4]

            sbn = hvd.SyncBatchNormalization(axis=-1, momentum=0.5)
            sbn.build((None, 3))
            ref = tf.keras.layers.BatchNormalization(axis=-1, momentum=0.5)
            ref.build((None, 3))

            with tf.GradientTape() as tape:
                out = sbn(tf.constant(mine), training=True)
                loss = tf.reduce_sum(tf.square(out) * 0.5)
            g_gamma, g_beta = tape.gradient(
                loss, [sbn.gamma, sbn.beta])
            # cross-process grads must then be summed (each rank saw its
            # shard only) to compare against the full-batch oracle.
            g_gamma = hvd.allreduce(g_gamma, op=hvd.Sum)
            g_beta = hvd.allreduce(g_beta, op=hvd.Sum)

            with tf.GradientTape() as rtape:
                rout = ref(tf.constant(full), training=True)
                rloss = tf.reduce_sum(tf.square(rout) * 0.5)
            rg_gamma, rg_beta = rtape.gradient(
                rloss, [ref.gamma, ref.beta])

            assert np.allclose(out.numpy(),
                               rout.numpy()[r * 4:(r + 1) * 4],
                               atol=1e-4), (out.numpy(), rout.numpy())
            assert np.allclose(g_gamma.numpy(), rg_gamma.numpy(),
                               atol=1e-3), (g_gamma, rg_gamma)
            assert np.allclose(g_beta.numpy(), rg_beta.numpy(),
                               atol=1e-3), (g_beta, rg_beta)
            # moving stats updated from the GLOBAL moments
            assert np.allclose(sbn.moving_mean.numpy(),
                               ref.moving_mean.numpy(), atol=1e-4)
            assert np.allclose(sbn.moving_variance.numpy(),
                               ref.moving_variance.numpy(), atol=1e-3)
            print("syncbn rank%d ok" % r)
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("syncbn rank0 ok" in l for l in lines), lines
        assert any("syncbn rank1 ok" in l for l in lines), lines

    def test_keras_bpps_tail_flush(self, tmp_path):
        """keras DistributedOptimizer with backward_passes_per_step=2 and
        an ODD apply count: _hvd_flush applies the tail window (averaged
        over the passes it holds) — weights match the expected closed
        form instead of silently dropping the last microbatch."""
        from horovod_tpu.runner.launch import (
            parse_args, run_static, settings_from_args,
        )

        script = _worker_script(
            tmp_path,
            """
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.keras as hvdk

            hvdk.init()
            r = hvdk.rank()
            v = tf.Variable([0.0])
            opt = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=1.0),
                backward_passes_per_step=2)
            # 3 applies of grad (r+1): two full-window passes -> one
            # update of mean over (2 passes x 2 ranks) = 1.5; the third
            # pass sits in the accumulator until flush.
            for _ in range(3):
                opt.apply_gradients([(tf.constant([float(r + 1)]), v)])
            assert np.allclose(v.numpy(), [-1.5]), v.numpy()
            opt._hvd_flush()  # tail window: 1 pass each, rank-avg 1.5
            assert np.allclose(v.numpy(), [-3.0]), v.numpy()
            # flush is a no-op when nothing is pending ANYWHERE (the
            # agreement collective returns total=0)
            assert opt._hvd_flush() is None

            # UNEVEN pending (the uneven-shard case): rank 0 has one
            # pending pass, rank 1 none — the flush must not hang; the
            # update is the mean over the ONE global pending pass.
            w = tf.Variable([0.0])
            opt2 = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=1.0),
                backward_passes_per_step=2)
            passes = 3 if r == 0 else 2
            for _ in range(passes):
                opt2.apply_gradients([(tf.constant([1.0]), w)])
            opt2._hvd_flush()
            # window 1 (both ranks): mean grad 1 -> -1; flush: rank 0's
            # single pending grad 1 over total=1 -> -1 more.
            assert np.allclose(w.numpy(), [-2.0]), (r, w.numpy())

            # Window-unused var keeps None-grad semantics at the flush:
            # fy trains in the FULL window (momentum buffer nonzero),
            # only fx is in the tail — a zero-grad apply would let
            # momentum keep moving fy.
            fx = tf.Variable([0.0], name="flushx")
            fy = tf.Variable([0.0], name="flushy")
            opt4 = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=1.0, momentum=0.9),
                backward_passes_per_step=2)
            one = tf.constant([1.0])
            for _ in range(2):
                opt4.apply_gradients([(one, fx), (one, fy)])
            fy_frozen = fy.numpy().copy()
            fx_window = fx.numpy().copy()
            opt4.apply_gradients([(one, fx)])  # tail: only fx
            opt4._hvd_flush()
            assert np.allclose(fy.numpy(), fy_frozen), (r, fy.numpy())
            assert not np.allclose(fx.numpy(), fx_window), fx.numpy()

            # ADVICE r4 regression: ranks accumulate the SAME variables
            # in DIFFERENT order (data-dependent None-grad history).
            # Wires pair by stable per-variable key, not position — a
            # positional pairing would silently average a's grad with
            # b's (both shapes match, no error raised).
            a = tf.Variable([0.0], name="wirekey_a")
            b = tf.Variable([0.0], name="wirekey_b")
            opt3 = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=1.0),
                backward_passes_per_step=2)
            ga, gb = tf.constant([1.0]), tf.constant([3.0])
            order = [(ga, a), (gb, b)] if r == 0 else [(gb, b), (ga, a)]
            opt3.apply_gradients(order)
            opt3.apply_gradients(order)
            assert np.allclose(a.numpy(), [-1.0]), (r, a.numpy())
            assert np.allclose(b.numpy(), [-3.0]), (r, b.numpy())
            print(f"kerasflush rank{r} ok", flush=True)
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", str(script)])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("kerasflush rank0 ok" in l for l in lines), lines
        assert any("kerasflush rank1 ok" in l for l in lines), lines

    def test_keras_none_grads_and_divergent_builtness(self, tmp_path):
        """ADVICE r3 regressions: (a) None grads (unconnected trainables)
        pass through the keras DistributedOptimizer unreduced instead of
        crashing _reduce_arrays; (b) ranks disagreeing on model builtness
        agree COLLECTIVELY before the broadcast exchange — built ranks
        must not enter collectives unbuilt ranks skip (the hang)."""
        from horovod_tpu.runner.launch import (
            parse_args, run_static, settings_from_args,
        )

        script = _worker_script(
            tmp_path,
            """
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.keras as hvdk

            hvdk.init()
            r = hvdk.rank()

            # (a) None-grad filtering: var "b" gets no gradient.
            a = tf.Variable([1.0 + r])
            b = tf.Variable([5.0])
            opt = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=1.0))
            opt.apply_gradients([(tf.constant([2.0 * (r + 1)]), a),
                                 (None, b)])
            # grads 2,4 -> avg 3; b untouched.
            assert np.allclose(a.numpy(), [1.0 + r - 3.0]), a.numpy()
            assert np.allclose(b.numpy(), [5.0]), b.numpy()

            # (b) divergent builtness: rank 0 builds BEFORE the callback
            # runs, rank 1 stays unbuilt. The agreement gate must defer
            # (no hang); once rank 1 builds, the broadcast completes.
            tf.random.set_seed(100 + r)
            model = tf.keras.Sequential([tf.keras.layers.Dense(2)])
            cb = hvdk.BroadcastGlobalVariablesCallback(0)
            cb.set_model(model)
            if r == 0:
                model.build((None, 3))
            cb.on_train_begin()       # divergent builtness: must defer
            assert not cb._done
            if r == 1:
                model.build((None, 3))
            cb.on_train_batch_end(0)  # all built now: exchange runs
            assert cb._done
            w = np.abs(model.get_weights()[0]).sum()
            print("kerasadvice rank%d digest %.6f" % (r, w))
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        digests = _parse_digests(lines, "kerasadvice")
        assert set(digests) == {0, 1}, lines
        assert digests[0] == pytest.approx(digests[1], abs=1e-6), digests

    def test_broadcast_callback_syncs_unbuilt_model(self, tmp_path):
        """An input-shape-less Sequential has no variables at
        on_train_begin; the callback must defer to first-batch-end and
        still converge every rank onto rank 0's weights (per-rank seeds
        prove it's the broadcast, not shared init)."""
        from horovod_tpu.runner.launch import (
            parse_args, run_static, settings_from_args,
        )

        script = _worker_script(
            tmp_path,
            """
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.keras as hvdk

            hvdk.init()
            r = hvdk.rank()
            tf.random.set_seed(100 + r)  # deliberately rank-divergent
            model = tf.keras.Sequential([
                tf.keras.layers.Dense(4, activation="relu"),
                tf.keras.layers.Dense(1),
            ])  # unbuilt: no input shape
            assert not model.trainable_variables
            # momentum creates optimizer slot variables: the deferred
            # broadcast must handle them (plus the int iterations var).
            model.compile(
                optimizer=hvdk.DistributedOptimizer(
                    tf.keras.optimizers.SGD(
                        learning_rate=0.0, momentum=0.9)),
                loss="mse", run_eagerly=True)
            rng = np.random.RandomState(0)  # same data on all ranks
            x = rng.rand(8, 3).astype(np.float32)
            y = rng.rand(8, 1).astype(np.float32)
            model.fit(
                x, y, batch_size=8, epochs=1, verbose=0,
                callbacks=[
                    hvdk.callbacks.BroadcastGlobalVariablesCallback(0)])
            # lr=0 and identical data: any weight difference now could
            # only come from divergent init -> broadcast must have run.
            digest = float(sum(
                np.abs(v.numpy()).sum()
                for v in model.trainable_variables))
            print("kerascb rank%d digest %.6f" % (r, digest))
            """,
        )
        args = parse_args(["-np", "2", "--cpu-mode", script])
        settings = settings_from_args(args)
        lines: list[str] = []
        rc = run_static(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        digests = _parse_digests(lines, "kerascb")
        assert set(digests) == {0, 1}, lines
        assert digests[0] == pytest.approx(digests[1], abs=1e-6), digests
