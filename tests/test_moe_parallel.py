"""Expert-parallel MoE: planner-priced, int8-quantized,
compute-overlapped alltoall wire (``parallel/moe.py``) — the ISSUE-16
acceptance proofs:

- the expert-parallel step matches the dense data-parallel oracle
  BITWISE under fp32 (full-world and sub-world expert sets, uneven
  token loads included) and within the documented tolerance under
  ``HOROVOD_MOE_COMPRESSION=int8``;
- per-rank resident expert bytes are 1/E of the replicated baseline;
- the dispatch alltoall interleaves with expert FFN compute in the
  jaxpr (``fusion.pipeline_interleave``);
- the planner's alltoall vocabulary: two_level selected on the
  emulated ``HOROVOD_LINK_CLASS_MAP`` split, bitwise-identical to flat
  (a permutation wire), non-pow2 island layouts, and bit-for-bit
  inertness with every knob unset;
- ``faults.MOE_DISPATCH`` (the canonical MoE chaos injector) and the
  ``hvd_moe_*`` / ``hvd_alltoall_latency_seconds`` instruments;
- the optimizer's expert-set-aware ReduceSpec: expert leaves allreduce
  only within their replica set.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu import faults, metrics, tracing
from horovod_tpu.ops import comms_planner as cp
from horovod_tpu.parallel import moe

N = 8
T, D, H, CAP = 16, 32, 48, 8


@pytest.fixture(autouse=True)
def _fresh_world(monkeypatch):
    """Cold planner, no MoE env knobs, clean fault registry."""
    monkeypatch.delenv("HOROVOD_COMMS_PLANNER", raising=False)
    monkeypatch.delenv("HOROVOD_LINK_CLASS_MAP", raising=False)
    monkeypatch.delenv("HOROVOD_MOE_COMPRESSION", raising=False)
    cp.reset_for_testing()
    faults.reset()
    yield
    cp.reset_for_testing()
    faults.reset()


def _inputs(seed=0, e=N, d=D):
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randn(N * T, d).astype(np.float32))
    gates_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    w1 = jnp.asarray(rng.randn(e, d, H).astype(np.float32))
    w2 = jnp.asarray(rng.randn(e, H, d).astype(np.float32))
    return tokens, gates_w, w1, w2


# ---------------------------------------------------------------------------
# Routing helpers
# ---------------------------------------------------------------------------


class TestRouting:
    def test_route_combine_roundtrip_identity(self):
        """Dispatch + identity 'expert' + combine reproduces kept
        tokens gated, dropped tokens passthrough."""
        tokens, gates_w, _, _ = _inputs()
        tok = tokens[:T]
        send, expert, pos, keep, gate, counts = moe.route_to_capacity(
            tok, tok @ gates_w, N, CAP)
        assert send.shape == (N, CAP, D + 1)
        assert int(counts.sum()) == int(keep.sum())
        out = moe.combine_from_capacity(send[..., :D], tok, expert, pos,
                                        keep, gate, CAP)
        want = np.where(np.asarray(keep)[:, None],
                        np.asarray(gate)[:, None] * np.asarray(tok),
                        np.asarray(tok))
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)

    def test_uneven_splits_rejection_names_helper(self, hvd):
        """Satellite 1: the jit rejection points at the capacity-factor
        routing helper."""
        with pytest.raises(NotImplementedError,
                           match="route_to_capacity"):
            jax.jit(
                jax.shard_map(
                    lambda v: hvd_mod.alltoall(v, splits=[1] * N),
                    mesh=hvd.global_mesh(),
                    in_specs=P(hvd.global_axis_name()),
                    out_specs=P(hvd.global_axis_name()),
                    check_vma=False,
                )
            ).lower(jnp.zeros((N * N, 2)))

    def test_expert_partition_patterns(self):
        from horovod_tpu import process_sets

        g, r = process_sets.expert_partition(None, 8)
        assert g == [[0, 1, 2, 3, 4, 5, 6, 7]] and len(r) == 8
        g, r = process_sets.expert_partition([0, 1, 2, 3], 8)
        assert g == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert r == [[0, 4], [1, 5], [2, 6], [3, 7]]
        g2, r2 = process_sets.expert_partition([0, 2, 4, 6], 8)
        assert sorted(sum(g2, [])) == list(range(8))
        assert all(len(grp) == 4 for grp in g2)
        for bad in ([], [0, 0], [1, 2], [3, 4, 5], [0, 1, 9]):
            with pytest.raises(ValueError):
                process_sets.expert_partition(bad, 8)

    def test_moe_compression_knob(self, monkeypatch):
        assert moe.moe_compression() is None
        assert moe.moe_compression("int8") == "int8"
        monkeypatch.setenv("HOROVOD_MOE_COMPRESSION", "int8")
        assert moe.moe_compression() == "int8"
        with pytest.raises(ValueError, match="HOROVOD_MOE_COMPRESSION"):
            moe.moe_compression("fp8")


# ---------------------------------------------------------------------------
# EP vs DP parity + trajectory
# ---------------------------------------------------------------------------


class TestParity:
    def test_ep_matches_dp_bitwise_fp32(self, hvd):
        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP, segments=2)
        dp = moe.make_data_parallel_moe_step(capacity=CAP, segments=2)
        out_ep = np.asarray(ep(tokens, gates_w, w1, w2))
        out_dp = np.asarray(dp(tokens, gates_w, w1, w2))
        np.testing.assert_array_equal(out_ep, out_dp)

    def test_ep_seg1_matches_legacy_layer_bitwise(self, hvd):
        tokens, gates_w, w1, w2 = _inputs()
        legacy = moe.make_moe_step(capacity=CAP)
        ep = moe.make_expert_parallel_moe_step(capacity=CAP, segments=1)
        np.testing.assert_array_equal(
            np.asarray(ep(tokens, gates_w, w1, w2)),
            np.asarray(legacy(tokens, gates_w, w1, w2)))

    def test_subworld_expert_set_matches_dp(self, hvd):
        """E=4 experts data-parallel over 2 dispatch groups."""
        tokens, gates_w, w1, w2 = _inputs(e=4)
        ep = moe.make_expert_parallel_moe_step(
            capacity=CAP, expert_set=[0, 1, 2, 3], segments=2)
        assert ep.num_experts == 4
        w1r = moe.replicate_expert_weights(w1, ep.expert_groups)
        w2r = moe.replicate_expert_weights(w2, ep.expert_groups)
        dp = moe.make_data_parallel_moe_step(capacity=CAP, segments=2)
        np.testing.assert_array_equal(
            np.asarray(ep(tokens, gates_w, w1r, w2r)),
            np.asarray(dp(tokens, gates_w, w1, w2)))

    def test_uneven_token_loads_all_to_one_expert(self, hvd):
        """Every token routed to expert 0: most drop past capacity, the
        passthrough residual carries them — and EP still matches DP."""
        tokens, _, w1, w2 = _inputs()
        # All-zero logits tie every column; argmax breaks ties to
        # expert 0, so EVERY token routes there.
        gates_w = jnp.zeros((D, N))
        before = metrics.MOE_TOKENS_DROPPED.labels().get()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP, segments=2)
        dp = moe.make_data_parallel_moe_step(capacity=CAP, segments=2)
        out_ep = np.asarray(ep(tokens, gates_w, w1, w2))
        np.testing.assert_array_equal(
            out_ep, np.asarray(dp(tokens, gates_w, w1, w2)))
        # 16 tokens/rank to one expert, capacity 8 -> 8 dropped/rank —
        # counted by BOTH the EP and the DP wrapper (one step each).
        assert (metrics.MOE_TOKENS_DROPPED.labels().get() - before
                == 2 * N * (T - CAP))

    def test_trajectory_fp32_exact_int8_tolerance(self, hvd):
        """Short token-recycling trajectory: fp32 EP tracks the DP
        oracle exactly; int8 stays within the documented tolerance."""
        tokens, gates_w, w1, w2 = _inputs(seed=3)
        ep = moe.make_expert_parallel_moe_step(capacity=CAP, segments=2)
        ep8 = moe.make_expert_parallel_moe_step(
            capacity=CAP, segments=2, compression="int8")
        dp = moe.make_data_parallel_moe_step(capacity=CAP, segments=2)
        t_ep, t_dp = tokens, tokens
        worst = 0.0
        for _ in range(3):
            out_dp = dp(t_dp, gates_w, w1, w2)
            t_ep = 0.5 * (t_ep + ep(t_ep, gates_w, w1, w2))
            # Teacher-forced int8 comparison along the oracle
            # trajectory: routing is discontinuous (int8 noise can flip
            # a borderline argmax to a different EXPERT), so free-running
            # divergence is chaotic, not a quantization-error measure.
            out_8 = ep8(t_dp, gates_w, w1, w2)
            scale = np.abs(np.asarray(out_dp)).max()
            worst = max(worst, float(
                np.abs(np.asarray(out_8) - np.asarray(out_dp)).max()
                / scale))
            t_dp = 0.5 * (t_dp + out_dp)
        np.testing.assert_array_equal(np.asarray(t_ep),
                                      np.asarray(t_dp))
        # Documented int8 tolerance (docs/perf.md): per-block scales
        # bound the round-trip error; 5% per step on random tokens.
        assert worst < 5e-2, worst

    def test_resident_expert_bytes_one_over_e(self, hvd):
        """EP shards the expert stack P(axis): each rank holds 1/E of
        the expert bytes the DP baseline replicates everywhere."""
        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP)
        ep(tokens, gates_w, w1, w2)
        mesh = hvd_mod.basics.global_mesh()
        from jax.sharding import NamedSharding

        w1_ep = jax.device_put(w1, NamedSharding(mesh, P("hvd")))
        shard_bytes = w1_ep.addressable_shards[0].data.nbytes
        assert shard_bytes * N == w1.nbytes  # 1/E per rank, E == n
        # DP keeps the full stack on every device.
        w1_dp = jax.device_put(w1, NamedSharding(mesh, P()))
        assert w1_dp.addressable_shards[0].data.nbytes == w1.nbytes


# ---------------------------------------------------------------------------
# Overlap: jaxpr-asserted interleaving
# ---------------------------------------------------------------------------


class TestOverlap:
    def test_dispatch_alltoall_interleaves_with_ffn(self, hvd):
        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP, segments=4)
        jaxpr = str(ep.jitted.trace(tokens, gates_w, w1, w2).jaxpr)
        first_dot = jaxpr.index("dot_general")
        last_dot = jaxpr.rindex("dot_general")
        a2a = [i for i in range(len(jaxpr))
               if jaxpr.startswith("all_to_all", i)]
        # 4 dispatch + 4 combine exchanges; at least one dispatch
        # alltoall sits BETWEEN expert FFN dot_generals — the
        # pipeline_interleave contract (segment i+1's wire before
        # segment i's compute).
        assert len(a2a) == 8
        assert any(first_dot < p < last_dot for p in a2a)

    def test_segments_clamp_to_capacity_divisor(self, hvd):
        ep = moe.make_expert_parallel_moe_step(capacity=6, segments=4)
        assert ep.meta["segments"] == 3  # largest divisor of 6 <= 4

    def test_pipeline_interleave_schedule(self):
        from horovod_tpu.ops import fusion

        order = []
        out = fusion.pipeline_interleave(
            3, lambda i: order.append(f"L{i}") or i,
            lambda i, li: order.append(f"C{i}") or (i, li))
        assert order == ["L0", "L1", "C0", "L2", "C1", "C2"]
        assert out == [(0, 0), (1, 1), (2, 2)]


# ---------------------------------------------------------------------------
# Planner: alltoall vocabulary
# ---------------------------------------------------------------------------


class TestPlanner:
    def test_two_level_selected_on_emulated_split(self, hvd,
                                                  monkeypatch):
        tokens, gates_w, w1, w2 = _inputs()
        ep_flat = moe.make_expert_parallel_moe_step(capacity=CAP,
                                                    segments=2)
        out_flat = np.asarray(ep_flat(tokens, gates_w, w1, w2))
        assert ep_flat.meta["algorithm"] == "flat"
        monkeypatch.setenv("HOROVOD_COMMS_PLANNER", "auto")
        monkeypatch.setenv("HOROVOD_LINK_CLASS_MAP", "0-3;4-7")
        cp.reset_for_testing()
        ep_tl = moe.make_expert_parallel_moe_step(capacity=CAP,
                                                  segments=2)
        out_tl = np.asarray(ep_tl(tokens, gates_w, w1, w2))
        assert ep_tl.meta["algorithm"] == "two_level"
        assert ep_tl.meta["link_class"] == "dcn"
        # A permutation wire: staged == flat BITWISE.
        np.testing.assert_array_equal(out_tl, out_flat)

    def test_int8_rides_the_staged_wire_bitwise_vs_flat(self, hvd,
                                                        monkeypatch):
        tokens, gates_w, w1, w2 = _inputs()
        ep_f8 = moe.make_expert_parallel_moe_step(
            capacity=CAP, segments=2, compression="int8")
        out_f8 = np.asarray(ep_f8(tokens, gates_w, w1, w2))
        monkeypatch.setenv("HOROVOD_COMMS_PLANNER", "auto")
        monkeypatch.setenv("HOROVOD_LINK_CLASS_MAP", "0-3;4-7")
        cp.reset_for_testing()
        ep_t8 = moe.make_expert_parallel_moe_step(
            capacity=CAP, segments=2, compression="int8")
        out_t8 = np.asarray(ep_t8(tokens, gates_w, w1, w2))
        assert ep_t8.meta["algorithm"] == "two_level"
        np.testing.assert_array_equal(out_t8, out_f8)

    def test_alltoall_pricing_crossover(self, monkeypatch):
        """α-side aggregation: two_level wins small buckets on a split
        fabric, flat wins above the crossover (β is identical — a
        permutation moves the same cross-DCN bytes either way)."""
        monkeypatch.setenv("HOROVOD_COMMS_PLANNER", "auto")
        monkeypatch.setenv("HOROVOD_LINK_CLASS_MAP", "0-3;4-7")
        small = cp.plan_bucket("alltoall", 64 << 10, N,
                               candidates=("flat", "two_level"))
        assert small.algorithm == "two_level"
        big = cp.plan_bucket("alltoall", 64 << 20, N,
                             candidates=("flat", "two_level"))
        assert big.algorithm == "flat"

    def test_rhd_never_eligible_for_alltoall(self):
        assert "rhd" not in cp.eligible_algorithms(
            "alltoall", N, ((0, 1, 2, 3), (4, 5, 6, 7)))
        # ... and adding the alltoall vocabulary didn't evict rhd from
        # the wire-op autotune axis.
        assert "rhd" in cp.eligible_algorithms("allreduce", N, None)

    def test_two_level_alltoall_bitwise_pow2_and_non_pow2(self, hvd):
        """Direct staged-vs-flat parity, integer payloads: regular 2x4
        split and a non-pow2 2x3 split on a 6-device sub-mesh."""
        for n, islands in ((8, ((0, 1, 2, 3), (4, 5, 6, 7))),
                           (6, ((0, 1, 2), (3, 4, 5)))):
            mesh = Mesh(np.array(jax.devices()[:n]), ("w",))
            x = jnp.arange(n * n * 3, dtype=jnp.int32).reshape(n * n, 3)

            def flat(v):
                from jax import lax

                return lax.all_to_all(v, "w", split_axis=0,
                                      concat_axis=0, tiled=True)

            def staged(v, islands=islands, n=n):
                chunks = v.reshape(n, v.shape[0] // n, *v.shape[1:])
                out = cp.two_level_alltoall(chunks, "w", islands)
                return out.reshape(v.shape)

            run = lambda f: np.asarray(jax.jit(jax.shard_map(  # noqa: E731
                f, mesh=mesh, in_specs=P("w"), out_specs=P("w"),
                check_vma=False))(x))
            np.testing.assert_array_equal(run(staged), run(flat))

    def test_knobs_unset_is_bit_for_bit_inert(self, hvd, monkeypatch):
        """Planner never consulted with the knob unset (poisoned
        plan_bucket), and the emitted program is identical to the
        planner-on-flat (uniform fabric) emission."""
        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP, segments=2)
        baseline = str(ep.jitted.lower(tokens, gates_w, w1,
                                       w2).as_text())

        def _poisoned(*a, **k):  # pragma: no cover - must not run
            raise AssertionError("planner consulted with knob unset")

        monkeypatch.setattr(cp, "plan_bucket", _poisoned)
        ep2 = moe.make_expert_parallel_moe_step(capacity=CAP,
                                                segments=2)
        assert str(ep2.jitted.lower(tokens, gates_w, w1,
                                    w2).as_text()) == baseline
        monkeypatch.undo()
        # Planner ON over a uniform fabric prices flat -> same program.
        monkeypatch.setenv("HOROVOD_COMMS_PLANNER", "auto")
        cp.reset_for_testing()
        ep3 = moe.make_expert_parallel_moe_step(capacity=CAP,
                                                segments=2)
        assert ep3.meta["segments"] == 2
        assert str(ep3.jitted.lower(tokens, gates_w, w1,
                                    w2).as_text()) == baseline

    def test_legacy_dp_path_ignores_moe_env_knobs(self, hvd,
                                                  monkeypatch):
        """HEAD's data-parallel MoE surface is byte-identical with the
        new knobs set: they are consumed only by the expert-parallel
        factory."""
        tokens, gates_w, w1, w2 = _inputs()
        legacy = moe.make_moe_step(capacity=CAP)
        baseline = str(legacy.lower(tokens, gates_w, w1, w2).as_text())
        monkeypatch.setenv("HOROVOD_MOE_COMPRESSION", "int8")
        monkeypatch.setenv("HOROVOD_COMMS_PLANNER", "auto")
        cp.reset_for_testing()
        legacy2 = moe.make_moe_step(capacity=CAP)
        assert str(legacy2.lower(tokens, gates_w, w1,
                                 w2).as_text()) == baseline


# ---------------------------------------------------------------------------
# Chaos + observability
# ---------------------------------------------------------------------------


class TestChaosAndMetrics:
    def test_moe_dispatch_drop_takes_passthrough(self, hvd):
        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP)
        clean = np.asarray(ep(tokens, gates_w, w1, w2))
        faults.inject(faults.MOE_DISPATCH, "drop", at=1, count=1)
        dropped = np.asarray(ep(tokens, gates_w, w1, w2))
        np.testing.assert_array_equal(dropped, np.asarray(tokens))
        assert faults.fired(faults.MOE_DISPATCH) == 1
        # Window exhausted: next step is clean again.
        np.testing.assert_array_equal(
            np.asarray(ep(tokens, gates_w, w1, w2)), clean)

    def test_moe_dispatch_corrupt_flips_payload_bits(self, hvd):
        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP)
        clean = np.asarray(ep(tokens, gates_w, w1, w2))
        faults.inject(faults.MOE_DISPATCH, "corrupt", at=1, count=1)
        bad = np.asarray(ep(tokens, gates_w, w1, w2))
        assert not np.array_equal(bad, clean)
        assert faults.fired(faults.MOE_DISPATCH) == 1

    def test_metrics_and_dispatch_markers(self, hvd):
        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP)
        tr = tracing.get_tracer()
        with tr.step_scope("train_step"):
            ep(tokens, gates_w, w1, w2)
        spans = tr.ring_snapshot()[-1]["spans"]
        names = [s["name"] for s in spans]
        algo = ep.meta["algorithm"]
        nb = ep.meta["nbytes"]
        assert any(n.startswith(f"moe.dispatch.{nb}B.{algo}")
                   for n in names)
        assert any(n.startswith(f"moe.combine.{nb}B.{algo}")
                   for n in names)
        dump = metrics.MOE_DISPATCH_BYTES.dump()
        assert dump["samples"][0]["count"] >= 1
        loads = {s["labels"]["expert"]: s["value"]
                 for s in metrics.MOE_EXPERT_LOAD.dump()["samples"]}
        assert len(loads) == N
        assert sum(loads.values()) > 0

    def test_dispatch_probe_feeds_latency_and_model(self, hvd):
        from horovod_tpu import comms_model as cm

        def _flat_count():
            for s in metrics.ALLTOALL_LATENCY.dump()["samples"]:
                if s["labels"] == {"algorithm": "flat"}:
                    return s["count"]
            return 0

        tokens, gates_w, w1, w2 = _inputs()
        ep = moe.make_expert_parallel_moe_step(capacity=CAP)
        ep(tokens, gates_w, w1, w2)  # populate meta
        before = _flat_count()
        out = ep.dispatch_probe(tokens, gates_w)
        assert np.asarray(out).shape == (N * N, CAP, D)
        assert _flat_count() == before + 1


# ---------------------------------------------------------------------------
# Optimizer: expert-set-aware ReduceSpec
# ---------------------------------------------------------------------------


class TestExpertOptimizer:
    def test_expert_leaves_reduce_within_replica_set(self, hvd):
        import optax

        from horovod_tpu import optimizer as opt

        dist = opt.DistributedOptimizer(
            optax.sgd(1.0), expert_set=[0, 1, 2, 3],
            expert_filter=lambda ks: "expert" in ks)
        spec = opt.reduce_spec_of(dist)
        assert spec.expert_set == [0, 1, 2, 3]
        params = {"dense": jnp.zeros((4,)),
                  "expert_w": jnp.zeros((4,))}
        mesh = hvd_mod.basics.global_mesh()

        def step(g):
            st = dist.init(params)
            upd, _ = dist.update(g, st, params)
            return upd

        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P("hvd"),), out_specs=P("hvd"),
            check_vma=False))
        g = jax.tree.map(
            lambda _: (jnp.arange(8.0)[:, None]
                       * jnp.ones((8, 4))).reshape(8, 4), params)
        upd = jax.tree.map(np.asarray, f(g))
        # Dense: world mean of 0..7 = 3.5 on every rank; expert:
        # replica sets {r, r+4} -> mean r+2 on ranks r and r+4.
        np.testing.assert_allclose(-upd["dense"].reshape(8, 4)[:, 0],
                                   np.full(8, 3.5))
        np.testing.assert_allclose(
            -upd["expert_w"].reshape(8, 4)[:, 0],
            [2.0, 3.0, 4.0, 5.0, 2.0, 3.0, 4.0, 5.0])

    def test_guard_table(self, hvd):
        import optax

        from horovod_tpu import optimizer as opt
        from horovod_tpu.exceptions import SyncModeIneligibleError

        flt = lambda ks: True  # noqa: E731
        with pytest.raises(SyncModeIneligibleError,
                           match="sync_mode='allreduce'"):
            opt.DistributedOptimizer(optax.sgd(1.0),
                                     sync_mode="sharded",
                                     expert_filter=flt)
        with pytest.raises(SyncModeIneligibleError,
                           match="backward_passes_per_step"):
            opt.DistributedOptimizer(optax.sgd(1.0),
                                     backward_passes_per_step=2,
                                     expert_filter=flt)
        with pytest.raises(ValueError, match="expert_filter"):
            opt.DistributedOptimizer(optax.sgd(1.0), expert_set=[0, 1])
