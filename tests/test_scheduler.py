"""Multi-tenant pod scheduler tests (ISSUE 17 acceptance proof).

Three layers, mirroring the subsystem's architecture:

- pool-tier and arbitration units under fake clocks: pool-wide
  condemnation evidence surviving a job handoff, cooldown expiry
  re-entering hosts as pool spares, priority-ordered victim selection
  with hysteresis (no A<->B thrash between two starving jobs), the
  three new fault points, and the multi-tenant observability surface
  (``/metrics`` zero-materialization, ``GET /pool``, the journal's
  ``job`` field, the job-aware log prefix);
- single-job inertness: with no scheduler and ``HOROVOD_JOB_ID`` unset,
  the log prefix, the endpoint record, and the journal schema are
  bit-for-bit those of HEAD;
- the chaos e2e with REAL processes — one scheduler, two elastic
  drivers, torch workers on a shared localhost pool: (a) SIGKILL a
  host's worker in job A and prove the pool spare heals A at its next
  generation fence with an exact loss trajectory while job B never sees
  an event; (b) SLO pressure on the high-priority job shrinks the
  low-priority job by one host through the drain -> final-commit ->
  reassign sequence, with exactly one ``sched_decision`` journal event
  per executed action carrying predicted + realized goodput.
"""

import json
import os
import signal
import sys
import textwrap
import threading
import time
import types
import urllib.request

import pytest

from horovod_tpu import faults
from horovod_tpu import metrics as hvd_metrics
from horovod_tpu.elastic.policy import JobArbiter
from horovod_tpu.runner.elastic.scheduler import (
    HostPool,
    JobSpec,
    MultiJobScheduler,
    SCHED_ACTIONS,
)
from horovod_tpu.utils.logging import rank_prefix

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _no_job_env(monkeypatch):
    monkeypatch.delenv("HOROVOD_JOB_ID", raising=False)


# ---------------------------------------------------------------------------
# Pool tier
# ---------------------------------------------------------------------------


class TestHostPool:
    def _pool(self, monkeypatch, clock, cooldown="600"):
        monkeypatch.setenv("HOROVOD_SCHED_BLACKLIST_COOLDOWN", cooldown)
        return HostPool(["h1", "h2", "h3"], clock=lambda: clock[0])

    def test_condemnation_evidence_survives_job_handoff(self, monkeypatch):
        """A host condemned by job A carries A's evidence in the pool
        record and is never handed to job B inside the cooldown."""
        clock = [0.0]
        pool = self._pool(monkeypatch, clock)
        assert pool.assign("h1", "jobA")
        pool.condemn("h1", "jobA", "worker failed with rc=-9")
        # The evidence rides the pool record, attributed to the
        # condemning job.
        rec = pool.condemned_record("h1")
        assert rec["job"] == "jobA"
        assert rec["reason"] == "worker failed with rc=-9"
        # Inside the cooldown: invisible to spares, unassignable to B.
        clock[0] = 599.0
        assert pool.prune() == []
        assert "h1" not in pool.spares()
        assert not pool.assign("h1", "jobB")
        assert pool.counts()["blacklisted"] == 1

    def test_cooldown_expiry_reenters_as_pool_spare(self, monkeypatch):
        clock = [0.0]
        pool = self._pool(monkeypatch, clock)
        pool.condemn("h2", "jobA", "drain: straggler")
        clock[0] = 600.5
        assert pool.prune() == ["h2"]
        assert "h2" in pool.spares()
        assert pool.assign("h2", "jobB")          # any job may take it
        assert pool.condemned_record("h2") is None

    def test_zero_cooldown_is_permanent(self, monkeypatch):
        clock = [0.0]
        pool = self._pool(monkeypatch, clock, cooldown="0")
        pool.condemn("h1", "jobA", "bad")
        clock[0] = 1e9
        assert pool.prune() == []
        assert "h1" not in pool.spares()

    def test_release_is_immediate_spare_reentry(self, monkeypatch):
        """A surplus host from a shrunk job re-enters WITHOUT evidence:
        it is a spare any job can promote, with no cooldown."""
        clock = [0.0]
        pool = self._pool(monkeypatch, clock)
        assert pool.assign("h3", "jobA")
        assert "h3" not in pool.spares()
        pool.release("h3")
        assert "h3" in pool.spares()
        assert pool.assign("h3", "jobB")

    def test_assign_refuses_taken_and_unknown_hosts(self, monkeypatch):
        clock = [0.0]
        pool = self._pool(monkeypatch, clock)
        assert pool.assign("h1", "jobA")
        assert not pool.assign("h1", "jobB")      # disjointness
        assert not pool.assign("nope", "jobB")

    def test_pool_assign_fault_point(self, monkeypatch):
        """faults: pool.assign drop mode holds the host back (returns
        False); the pool record is untouched."""
        clock = [0.0]
        pool = self._pool(monkeypatch, clock)
        faults.inject(faults.POOL_ASSIGN, "drop", at=1, count=1)
        assert not pool.assign("h1", "jobA")
        assert faults.fired(faults.POOL_ASSIGN) == 1
        assert "h1" in pool.spares()              # held back, not burned
        assert pool.assign("h1", "jobA")          # next tick succeeds

    def test_export_carries_relative_evidence_ages(self, monkeypatch):
        clock = [0.0]
        pool = self._pool(monkeypatch, clock)
        pool.condemn("h2", "jobA", "bad link")
        clock[0] = 12.5
        by_name = {h["host"]: h for h in pool.export()}
        assert by_name["h2"]["condemned"]["age_s"] == pytest.approx(12.5)
        assert by_name["h2"]["condemned"]["job"] == "jobA"
        assert by_name["h1"]["condemned"] is None

    def test_host_slots_parse(self):
        pool = HostPool(["h1:4", "h2"])
        assert pool.slots_of("h1") == 4
        assert pool.slots_of("h2") == 1


# ---------------------------------------------------------------------------
# Cross-job arbitration
# ---------------------------------------------------------------------------


def _arbiter(monkeypatch, clock, hysteresis="10", cooldown="30",
             pin=None):
    monkeypatch.setenv("HOROVOD_SCHED_HYSTERESIS", hysteresis)
    monkeypatch.setenv("HOROVOD_SCHED_COOLDOWN", cooldown)
    if pin is not None:
        monkeypatch.setenv("HOROVOD_SCHED_PIN_COOLDOWN", pin)
    return JobArbiter(clock=lambda: clock[0])


class TestJobArbiter:
    def test_hysteresis_gates_sustained_starvation(self, monkeypatch):
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        a.note_job("lo", 3, 1, 4, priority=1, target=0.5)
        assert a.decide(0) is None                # not sustained yet
        clock[0] = 9.0
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        assert a.decide(0) is None
        clock[0] = 10.5
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        d = a.decide(0)
        assert d is not None and d.action == "shrink"
        assert d.victim == "lo" and d.recipient == "hi"
        assert d.predicted["recipient"]["goodput_after"] == 0.5

    def test_recovery_resets_the_hysteresis_clock(self, monkeypatch):
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        clock[0] = 8.0
        a.note_job("hi", 4, 2, 4, priority=10, target=0.9)  # healed...
        clock[0] = 9.0
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)  # ...starves
        a.note_job("lo", 3, 1, 4, priority=1, target=0.5)
        clock[0] = 12.0
        assert a.decide(0) is None        # fresh clock: 3s < 10s

    def test_pool_spare_preempts_arbitration(self, monkeypatch):
        """With a promotable spare the pool heals — no victim needed."""
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        a.note_job("lo", 3, 1, 4, priority=1, target=0.5)
        clock[0] = 20.0
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        assert a.decide(1) is None
        assert a.decide(0) is not None

    def test_victim_order_priority_then_surplus(self, monkeypatch):
        """Victims in priority order (lowest first), then furthest over
        SLO — the ISSUE's 'furthest OVER its SLO by priority order'."""
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        a.note_job("hi", 1, 2, 6, priority=10, target=0.9)
        a.note_job("mid", 4, 1, 4, priority=5, target=0.5)   # over SLO
        a.note_job("lo", 4, 1, 4, priority=1, target=0.9)    # over SLO
        clock[0] = 20.0
        a.note_job("hi", 1, 2, 6, priority=10, target=0.9)
        d = a.decide(0)
        assert d.victim == "lo"           # lowest priority yields first

    def test_no_thrash_between_two_starving_equals(self, monkeypatch):
        """Two equal-priority starving jobs must never trade hosts: a
        job under its own SLO only yields to a strictly higher-priority
        recipient, so neither qualifies as the other's victim."""
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        for t in (0.0, 15.0, 30.0, 60.0, 120.0):
            clock[0] = t
            a.note_job("a", 2, 1, 4, priority=5, target=0.9)
            a.note_job("b", 2, 1, 4, priority=5, target=0.9)
            assert a.decide(0) is None

    def test_shrink_respects_min_np_else_preempts_lower_priority(
            self, monkeypatch):
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        a.note_job("lo", 2, 2, 4, priority=1, target=0.5)
        clock[0] = 20.0
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        d = a.decide(0)
        assert d.action == "preempt"      # 2-1 < min_np=2: full preempt
        assert d.victim == "lo"
        assert d.predicted["victim"]["goodput_after"] == 0.0

    def test_priority_monotonicity_is_structural(self, monkeypatch):
        """Hosts only flow UP the priority gradient: a starving
        low-priority job never victimizes a higher-priority job, even
        one comfortably over its own SLO — transfer cycles are
        impossible by construction, not merely throttled."""
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        a.note_job("hi", 4, 2, 6, priority=10, target=0.65)  # satisfied
        a.note_job("lo", 1, 1, 2, priority=1, target=0.9)    # starving
        clock[0] = 60.0
        a.note_job("lo", 1, 1, 2, priority=1, target=0.9)
        assert a.decide(0) is None        # sustained, but no victim

    def test_action_cooldown_and_recipient_pin(self, monkeypatch):
        """After an executed action: the cooldown throttles the next
        pass, and the healed recipient is pinned against being
        re-victimized by a still-higher-priority job for one pin
        window — the second layer of the anti-thrash contract."""
        clock = [0.0]
        a = _arbiter(monkeypatch, clock, hysteresis="10", cooldown="30",
                     pin="1000")
        a.note_job("mid", 1, 1, 4, priority=5, target=0.9)
        a.note_job("lo", 4, 1, 4, priority=1, target=0.5)
        clock[0] = 15.0
        a.note_job("mid", 1, 1, 4, priority=5, target=0.9)
        d = a.decide(0)
        assert d is not None and d.victim == "lo"
        assert d.recipient == "mid"
        a.record_action(d)                # pins 'mid', arms cooldown
        clock[0] = 20.0                   # inside the 30s cooldown
        assert a.decide(0) is None
        a.forget_job("lo")
        clock[0] = 50.0                   # cooldown over; 'top' starves
        a.note_job("top", 1, 2, 4, priority=10, target=0.9)
        a.note_job("mid", 2, 1, 4, priority=5, target=0.9)
        clock[0] = 61.0
        a.note_job("top", 1, 2, 4, priority=10, target=0.9)
        # 'mid' (priority 5 < 10) is the only candidate, but it just
        # received the transfer: pinned — no immediate claw-back.
        assert a.decide(0) is None
        clock[0] = 1020.0                 # pin window over
        a.note_job("top", 1, 2, 4, priority=10, target=0.9)
        d = a.decide(0)
        assert d is not None and d.victim == "mid"

    def test_sched_decide_fault_point(self, monkeypatch):
        """faults: sched.decide drop mode skips the arbitration pass."""
        clock = [0.0]
        a = _arbiter(monkeypatch, clock)
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        a.note_job("lo", 3, 1, 4, priority=1, target=0.5)
        clock[0] = 20.0
        a.note_job("hi", 1, 2, 4, priority=10, target=0.9)
        faults.inject(faults.SCHED_DECIDE, "drop", at=1, count=1)
        assert a.decide(0) is None
        assert faults.fired(faults.SCHED_DECIDE) == 1
        assert a.decide(0) is not None    # next pass decides

    def test_new_fault_points_parse_from_env_grammar(self):
        """The scheduler-plane injection points ride the standard
        HOROVOD_FAULTS grammar (point=mode[:arg]@N[xC])."""
        from horovod_tpu.faults import parse_spec

        specs = parse_spec(
            "sched.decide=drop@1; job.preempt=raise@2x3; "
            "pool.assign=delay:0.5@1")
        by = {s.point: s for s in specs}
        assert by[faults.SCHED_DECIDE].mode == "drop"
        assert by[faults.JOB_PREEMPT].mode == "raise"
        assert by[faults.JOB_PREEMPT].at == 2
        assert by[faults.JOB_PREEMPT].count == 3
        assert by[faults.POOL_ASSIGN].mode == "delay"


# ---------------------------------------------------------------------------
# Scheduler units (no subprocesses)
# ---------------------------------------------------------------------------


def _specs():
    return [
        JobSpec(job_id="alpha", command=["true"], min_np=2, max_np=4,
                priority=10, target_goodput=0.9),
        JobSpec(job_id="beta", command=["true"], min_np=1, max_np=2,
                priority=1),
    ]


class TestSchedulerUnits:
    def test_shrink_blacklist_is_drain_completion_not_evidence(
            self, tmp_path):
        """The victim driver blacklists the host the scheduler itself is
        draining (the preempt-notice path): that event advances the
        in-flight shrink — it must NOT condemn the healthy host."""
        sched = MultiJobScheduler(_specs(), ["h1", "h2", "h3"],
                                  str(tmp_path))
        beta = sched._jobs["beta"]
        beta.state = "running"
        beta.lease = ["h2"]
        sched._pool.assign("h2", "beta")
        sched._pending.append({
            "action": "shrink", "job": "alpha", "victim": "beta",
            "host": "h2", "stage": "drain", "reason": "r",
            "predicted": {}, "deadline": 1e18})
        sched._handle_job_event(beta, {
            "event": "blacklist", "host": "h2",
            "reason": "preempt: external preemption notice"})
        assert sched._pending[0]["stage"] == "reassign"
        assert sched._pool.condemned_record("h2") is None

    def test_worker_crash_blacklist_condemns_pool_wide(self, tmp_path):
        sched = MultiJobScheduler(_specs(), ["h1", "h2", "h3"],
                                  str(tmp_path))
        alpha = sched._jobs["alpha"]
        alpha.state = "running"
        alpha.lease = ["h1", "h2"]
        sched._pool.assign("h1", "alpha")
        sched._pool.assign("h2", "alpha")
        sched._handle_job_event(alpha, {
            "event": "blacklist", "host": "h2",
            "reason": "worker failed with rc=-9"})
        rec = sched._pool.condemned_record("h2")
        assert rec["job"] == "alpha"
        assert "rc=-9" in rec["reason"]
        assert alpha.lease == ["h1"]      # lease rewritten without it
        assert not sched._pool.assign("h2", "beta")

    def test_job_preempt_fault_point_holds_the_sigterm(self, tmp_path):
        from horovod_tpu.elastic.policy import ArbiterDecision

        sched = MultiJobScheduler(_specs(), ["h1", "h2"], str(tmp_path))
        beta = sched._jobs["beta"]
        beta.state = "running"
        signals = []
        beta.proc = types.SimpleNamespace(
            send_signal=signals.append, poll=lambda: None)
        d = ArbiterDecision(action="preempt", victim="beta",
                            recipient="alpha", reason="r", predicted={})
        faults.inject(faults.JOB_PREEMPT, "drop", at=1, count=1)
        sched._actuate_preempt(d)
        assert signals == [] and beta.state == "running"
        sched._actuate_preempt(d)         # injector exhausted: executes
        assert signals == [signal.SIGTERM]
        assert beta.state == "preempting"

    def test_metrics_and_pool_endpoints(self, tmp_path):
        """The observability surface, served over real HTTP: the pool
        and job gauges plus the decision counter zero-materialized on
        /metrics, and GET /pool carrying >= 2 job entries with
        world/goodput/SLO state — what premerge gate 4 scrapes."""
        sched = MultiJobScheduler(_specs(), ["h1", "h2", "h3"],
                                  str(tmp_path))
        sched._start_http()
        try:
            base = f"http://127.0.0.1:{sched.port}"
            text = urllib.request.urlopen(
                f"{base}/metrics", timeout=10).read().decode()
            parsed = hvd_metrics.validate_prometheus_text(text)
            assert parsed["hvd_pool_hosts"]["samples"] == [({}, 3.0)]
            assert parsed["hvd_pool_spares"]["samples"] == [({}, 3.0)]
            assert parsed["hvd_pool_blacklisted"]["samples"] == [
                ({}, 0.0)]
            assert parsed["hvd_jobs_running"]["samples"] == [({}, 0.0)]
            assert parsed["hvd_jobs_preempted_total"]["samples"] == [
                ({}, 0.0)]
            actions = {l["action"]: v for l, v in
                       parsed["hvd_sched_decisions_total"]["samples"]}
            assert actions == {a: 0.0 for a in SCHED_ACTIONS}
            pool = json.loads(urllib.request.urlopen(
                f"{base}/pool", timeout=10).read().decode())
            assert len(pool["jobs"]) == 2
            assert pool["jobs"]["alpha"]["target_goodput"] == 0.9
            assert pool["jobs"]["alpha"]["state"] == "pending"
            assert len(pool["hosts"]) == 3
            assert pool["spares"] == ["h1", "h2", "h3"]
        finally:
            sched._httpd.shutdown()
            sched._httpd.server_close()

    def test_duplicate_job_id_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            MultiJobScheduler(
                [JobSpec(job_id="x", command=["true"], min_np=1,
                         max_np=1)] * 2, ["h1"], str(tmp_path))


# ---------------------------------------------------------------------------
# Single-job inertness + the job dimension (satellites 1 and 6)
# ---------------------------------------------------------------------------


class TestJobDimension:
    def test_log_prefix_unchanged_without_job(self, monkeypatch):
        """HEAD's exact prefix forms when HOROVOD_JOB_ID is unset."""
        for var in ("HOROVOD_JOB_ID", "HOROVOD_RANK", "HOROVOD_SIZE",
                    "HOROVOD_ELASTIC", "HOROVOD_WORLD_VERSION"):
            monkeypatch.delenv(var, raising=False)
        assert rank_prefix() == ""
        monkeypatch.setenv("HOROVOD_RANK", "1")
        monkeypatch.setenv("HOROVOD_SIZE", "4")
        assert rank_prefix() == "[1/4] "
        monkeypatch.setenv("HOROVOD_ELASTIC", "1")
        monkeypatch.setenv("HOROVOD_WORLD_VERSION", "3")
        assert rank_prefix() == "[1/4 g3] "

    def test_log_prefix_gains_job_dimension(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_JOB_ID", "trainA")
        monkeypatch.delenv("HOROVOD_RANK", raising=False)
        assert rank_prefix() == "[trainA] "          # driver-side form
        monkeypatch.setenv("HOROVOD_RANK", "0")
        monkeypatch.setenv("HOROVOD_SIZE", "2")
        monkeypatch.setenv("HOROVOD_ELASTIC", "1")
        monkeypatch.setenv("HOROVOD_WORLD_VERSION", "5")
        assert rank_prefix() == "[trainA/0/2 g5] "

    def test_journal_job_field_null_then_stamped(self, tmp_path,
                                                 monkeypatch):
        """Every journal record carries ``job``: null outside a
        scheduled job (the documented single-job schema), the env job id
        inside one — re-read per record, and an explicit ``job=`` field
        (the scheduler's own events) wins."""
        jpath = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(jpath))
        hvd_metrics.event("unit_a")
        monkeypatch.setenv("HOROVOD_JOB_ID", "jobZ")
        hvd_metrics.event("unit_b")
        hvd_metrics.event("unit_c", job="explicit")
        recs = [json.loads(l) for l in
                jpath.read_text().splitlines()]
        by = {r["event"]: r for r in recs}
        assert by["unit_a"]["job"] is None
        assert by["unit_b"]["job"] == "jobZ"
        assert by["unit_c"]["job"] == "explicit"

    def test_endpoint_record_byte_identical_without_job(
            self, tmp_path, monkeypatch):
        from horovod_tpu.runner.elastic.driver_state import (
            DriverStateStore, read_endpoint)

        store = DriverStateStore(str(tmp_path), epoch=1)
        store.publish_endpoint("127.0.0.1", 1234, generation=2)
        rec = read_endpoint(str(tmp_path))
        assert set(rec) == {"addr", "port", "driver_epoch", "generation"}
        monkeypatch.setenv("HOROVOD_JOB_ID", "jobQ")
        store.publish_endpoint("127.0.0.1", 1234, generation=3)
        rec = read_endpoint(str(tmp_path))
        assert rec["job"] == "jobQ"


# ---------------------------------------------------------------------------
# Chaos e2e: real scheduler, real drivers, real workers, shared pool
# ---------------------------------------------------------------------------

POOL = ["127.0.0.2", "127.0.0.3", "127.0.0.4", "127.0.0.5", "127.0.0.6"]


def _elastic_worker(tmp_path) -> str:
    """Elastic torch SGD worker (the test_policy harness shape): exact
    per-(epoch, rank) seeded batches so a 2-rank trajectory has a closed
    -form oracle; writes a pidfile per (job, host) so the test can
    SIGKILL a specific host's worker; an allreduced stop-file check so
    open-ended jobs end on the SAME epoch on every rank."""
    path = tmp_path / "elastic_worker.py"
    path.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO_ROOT!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from horovod_tpu._jax_compat import force_cpu_devices
        force_cpu_devices(1)
        import numpy as np
        import torch
        import horovod_tpu.torch as hvd
        from horovod_tpu.elastic import run as elastic_run
        from horovod_tpu.torch.elastic import TorchState

        host = os.environ["HOROVOD_HOSTNAME"]
        job = os.environ["HOROVOD_JOB_ID"]
        piddir = os.environ["TEST_PID_DIR"]
        with open(os.path.join(piddir, f"pid.{{job}}.{{host}}"),
                  "w") as f:
            f.write(str(os.getpid()))
        EPOCHS = int(os.environ["TEST_EPOCHS"])
        STOP_FILE = os.environ.get("TEST_STOP_FILE", "")
        STEP_SLEEP = float(os.environ["TEST_STEP_SLEEP"])

        torch.manual_seed(0)
        model = torch.nn.Linear(4, 1, bias=False)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters())
        state = TorchState(model=model, optimizer=opt, epoch=0)

        @elastic_run
        def train(state):
            while state.epoch < EPOCHS:
                if STOP_FILE:
                    # Allreduced so every rank stops at the SAME epoch.
                    flag = torch.tensor(
                        [1.0 if os.path.exists(STOP_FILE) else 0.0])
                    if float(hvd.allreduce(flag, name="stop")) > 0:
                        break
                time.sleep(STEP_SLEEP)
                r = hvd.rank()
                x = torch.from_numpy(np.random.RandomState(
                    100 * state.epoch + r).randn(8, 4).astype(
                        np.float32))
                opt.zero_grad()
                loss = (model(x) ** 2).mean()
                loss.backward()
                opt.step()
                print("rank=%d host=%s epoch=%d np=%d loss=%.6f" % (
                    r, host, state.epoch, hvd.size(), float(loss)),
                    flush=True)
                state.epoch += 1
                state.commit()
            return state.epoch

        done = train(state)
        print("host=%s finished at epoch %d" % (host, done), flush=True)
    """))
    return str(path)


def _expected_losses(epochs: int) -> dict:
    """The exact 2-rank averaged-SGD loss schedule (host-independent)."""
    import numpy as np
    import torch

    torch.manual_seed(0)
    m = torch.nn.Linear(4, 1, bias=False)
    sgd = torch.optim.SGD(m.parameters(), lr=0.05)
    expected = {}
    for e in range(epochs):
        grads = []
        for r in range(2):
            x = torch.from_numpy(np.random.RandomState(
                100 * e + r).randn(8, 4).astype(np.float32))
            sgd.zero_grad()
            loss = (m(x) ** 2).mean()
            expected[(e, r)] = float(loss.detach())
            loss.backward()
            grads.append([p.grad.clone() for p in m.parameters()])
        with torch.no_grad():
            for p, g0, g1 in zip(m.parameters(), *grads):
                p.grad = (g0 + g1) / 2
        sgd.step()
    return expected


def _assert_loss_continuity(text: str, epochs: int):
    import re

    expected = _expected_losses(epochs)
    seen = set()
    # finditer over the whole text: the drivers' stdout relay can very
    # occasionally land two workers' lines on one physical line.
    for m in re.finditer(
            r"rank=(\d+) host=\S+ epoch=(\d+) np=2 loss=([0-9.]+)", text):
        r, e, got = int(m.group(1)), int(m.group(2)), float(m.group(3))
        assert abs(got - expected[(e, r)]) < 1e-4, (
            e, r, got, expected[(e, r)])
        seen.add((e, r))
    missing = {(e, r) for e in range(epochs) for r in (0, 1)} - seen
    assert not missing, sorted(missing)[:10]


def _job_records(path: str) -> list[dict]:
    records = []
    if os.path.exists(path):
        for line in open(path, encoding="utf-8"):
            try:
                records.append(json.loads(line))
            except ValueError:
                pass
    return records


def _sched_env(monkeypatch, tmp_path):
    monkeypatch.setenv("HOROVOD_EVENT_LOG",
                       str(tmp_path / "sched_events.jsonl"))
    monkeypatch.setenv("HOROVOD_SCHED_TICK", "0.25")
    monkeypatch.setenv("HOROVOD_ELASTIC_HEARTBEAT_INTERVAL", "0.25")
    monkeypatch.setenv("HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", "30")
    monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN", "600")
    # Wide enough that a cold-starting promoted worker's first native
    # attempt overlaps the surviving rank's accept window even when the
    # box is busy (a 6s window can phase-lock-miss under load).
    monkeypatch.setenv("HOROVOD_NATIVE_INIT_TIMEOUT", "15")
    monkeypatch.setenv("HOROVOD_SCHED_REALIZE_TIMEOUT", "90")


def _run_sched_in_thread(sched):
    result = {}

    def go():
        result["rc"] = sched.run()

    t = threading.Thread(target=go, name="sched-run", daemon=True)
    t.start()
    return t, result


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.2)
    pytest.fail(f"timed out waiting for {what}")


@pytest.mark.slow
class TestMultiTenantPodE2E:
    def test_host_kill_heals_from_pool_spare_other_job_untouched(
            self, tmp_path, monkeypatch):
        """Scenario (a): two gangs on a shared pool, one spare. SIGKILL
        the worker on one of job A's hosts: A's driver blacklists it,
        the scheduler condemns it POOL-WIDE (evidence carried) and
        promotes the pool spare into A's lease; A republishes at g+1
        with the spare, its loss trajectory stays exact against the
        uninterrupted 2-rank oracle, and job B never observes an
        event."""
        pytest.importorskip("torch")
        epochs = 120
        _sched_env(monkeypatch, tmp_path)
        monkeypatch.setenv("TEST_PID_DIR", str(tmp_path))
        worker = _elastic_worker(tmp_path)
        common = dict(
            command=[sys.executable, worker], min_np=2, max_np=2,
            cpu_mode=True, elastic_timeout=90.0,
            env={"TEST_PID_DIR": str(tmp_path),
                 "TEST_EPOCHS": str(epochs),
                 "TEST_STEP_SLEEP": "0.05"})
        sched = MultiJobScheduler(
            [JobSpec(job_id="aaa", priority=5, **common),
             JobSpec(job_id="bbb", priority=5, **common)],
            POOL, str(tmp_path / "pod"))
        thread, result = _run_sched_in_thread(sched)

        # Both gangs formed: every leased host's worker wrote a pidfile.
        _wait(lambda: sched._jobs["aaa"].world is not None
              and sched._jobs["bbb"].world is not None,
              90, "both jobs to publish a world")
        lease_a = list(sched._jobs["aaa"].lease)
        doomed = lease_a[1]
        pidfile = tmp_path / f"pid.aaa.{doomed}"
        _wait(pidfile.exists, 60, "the doomed worker's pidfile")
        spare_before = sched._pool.spares()
        assert len(spare_before) == 1
        time.sleep(1.0)                    # let a few epochs land
        os.kill(int(pidfile.read_text()), signal.SIGKILL)

        thread.join(timeout=240)
        assert not thread.is_alive(), "scheduler never finished"
        assert result["rc"] == 0

        pod = tmp_path / "pod"
        sched_records = _job_records(str(tmp_path / "sched_events.jsonl"))
        a_records = _job_records(str(pod / "aaa" / "events.jsonl"))
        b_records = _job_records(str(pod / "bbb" / "events.jsonl"))
        a_log = (pod / "aaa" / "driver.log").read_text(errors="replace")
        b_log = (pod / "bbb" / "driver.log").read_text(errors="replace")

        # A's driver condemned the host; the evidence reached the pool.
        blk = [r for r in a_records if r["event"] == "blacklist"
               and r["host"] == doomed]
        assert blk, a_records
        # The coordinated abort fired in A (the survivors' recovery
        # trigger) — never in B.
        assert any(r["event"] == "abort_posted" for r in a_records)
        assert all(r["job"] == "aaa" for r in a_records), \
            [r for r in a_records if r["job"] != "aaa"][:3]
        cond = [r for r in sched_records if r["event"] == "sched_pool"
                and r.get("change") == "condemned"]
        assert len(cond) == 1 and cond[0]["host"] == doomed
        assert cond[0]["job"] == "aaa"
        rec = sched._pool.condemned_record(doomed)
        assert rec is not None and rec["job"] == "aaa", rec

        # The pool spare healed A at its next generation fence: exactly
        # one promote decision, realized in A's republished world.
        promotes = [r for r in sched_records
                    if r["event"] == "sched_decision"
                    and r["action"] == "promote"]
        assert len(promotes) == 1, sched_records
        assert promotes[0]["host"] == spare_before[0]
        assert promotes[0]["job"] == "aaa"
        assert promotes[0]["realized"] is not None, promotes
        worlds_a = [r for r in a_records
                    if r["event"] == "world_published"]
        assert len(worlds_a) >= 2
        assert spare_before[0] in worlds_a[-1]["hosts"]
        assert all(w["np"] == 2 for w in worlds_a), worlds_a

        # No arbitration was needed: the pool healed it.
        actions = {r["action"] for r in sched_records
                   if r["event"] == "sched_decision"}
        assert actions == {"grant", "promote"}, actions
        grants = [r for r in sched_records
                  if r["event"] == "sched_decision"
                  and r["action"] == "grant"]
        assert len(grants) == 2

        # Job B: one world, zero elastic events, untouched trajectory.
        worlds_b = [r for r in b_records
                    if r["event"] == "world_published"]
        assert len(worlds_b) == 1, worlds_b
        assert not any(r["event"] in ("blacklist", "abort_posted",
                                      "policy_drain", "recovery")
                       for r in b_records), b_records
        assert set(worlds_b[0]["hosts"]).isdisjoint(
            set(worlds_a[-1]["hosts"]))

        # Loss exactness for BOTH jobs against the uninterrupted oracle
        # (A replayed across the re-form; B never re-formed).
        _assert_loss_continuity(a_log, epochs)
        _assert_loss_continuity(b_log, epochs)

    def test_slo_pressure_shrinks_low_priority_job(self, tmp_path,
                                                   monkeypatch):
        """Scenario (b): both jobs under SLO pressure on a full pool.
        The arbiter shrinks the LOW-priority job by one host through
        the drain -> final-commit -> reassign sequence; the
        high-priority job heals at its next fence; exactly one
        ``sched_decision`` journal event per executed action, each with
        predicted + realized goodput; both jobs then run to a clean
        rc=0."""
        pytest.importorskip("torch")
        _sched_env(monkeypatch, tmp_path)
        monkeypatch.setenv("HOROVOD_SCHED_HYSTERESIS", "2")
        monkeypatch.setenv("HOROVOD_SCHED_COOLDOWN", "8")
        stop_file = tmp_path / "stop"
        worker = _elastic_worker(tmp_path)
        common = dict(
            command=[sys.executable, worker], cpu_mode=True,
            elastic_timeout=90.0,
            env={"TEST_PID_DIR": str(tmp_path),
                 "TEST_EPOCHS": "100000",
                 "TEST_STOP_FILE": str(stop_file),
                 "TEST_STEP_SLEEP": "0.1"})
        sched = MultiJobScheduler(
            [JobSpec(job_id="hi", priority=10, min_np=2, max_np=6,
                     target_goodput=0.65, **common),
             JobSpec(job_id="lo", priority=1, min_np=1, max_np=2,
                     target_goodput=0.9, **common)],
            POOL, str(tmp_path / "pod"))
        thread, result = _run_sched_in_thread(sched)

        # The shrink realizes: 'lo' yields one host, 'hi' adopts it.
        def shrink_realized():
            recs = _job_records(str(tmp_path / "sched_events.jsonl"))
            return any(r["event"] == "sched_decision"
                       and r["action"] == "shrink"
                       for r in recs)

        _wait(shrink_realized, 180, "the shrink decision to realize")
        stop_file.write_text("now")
        thread.join(timeout=240)
        assert not thread.is_alive(), "scheduler never finished"
        assert result["rc"] == 0

        pod = tmp_path / "pod"
        sched_records = _job_records(str(tmp_path / "sched_events.jsonl"))
        lo_records = _job_records(str(pod / "lo" / "events.jsonl"))
        hi_records = _job_records(str(pod / "hi" / "events.jsonl"))

        decisions = [r for r in sched_records
                     if r["event"] == "sched_decision"]
        by_action = {}
        for r in decisions:
            by_action.setdefault(r["action"], []).append(r)
        # Exactly one sched_decision per executed action: two gang
        # grants, two spare promotions (the initial fill), one shrink.
        assert len(by_action["grant"]) == 2
        assert len(by_action["shrink"]) == 1, decisions
        assert "preempt" not in by_action, decisions
        for r in decisions:
            assert r["predicted"] is not None, r
            assert r["realized"] is not None, r

        shrink = by_action["shrink"][0]
        assert shrink["victim"] == "lo" and shrink["job"] == "hi"
        pred = shrink["predicted"]
        assert pred["recipient"]["goodput_after"] > \
            pred["recipient"]["goodput_before"]
        assert shrink["realized"]["victim_goodput"] < \
            pred["victim"]["goodput_before"]
        moved = shrink["host"]

        # The victim drained the host through the final-commit preempt
        # path (the driver's policy_drain with action=preempt), then
        # republished at its own g+1 without it — never below min_np.
        drains = [r for r in lo_records if r["event"] == "policy_drain"]
        assert len(drains) == 1 and drains[0]["host"] == moved
        assert drains[0]["action"] == "preempt"
        lo_worlds = [r for r in lo_records
                     if r["event"] == "world_published"]
        assert lo_worlds[-1]["np"] >= 1
        assert moved not in lo_worlds[-1]["hosts"]

        # The recipient adopted the SAME host at its next fence.
        hi_worlds = [r for r in hi_records
                     if r["event"] == "world_published"]
        assert moved in hi_worlds[-1]["hosts"], hi_worlds
        assert hi_worlds[-1]["np"] > hi_worlds[0]["np"]

        # Journal job dimension: every job-journal record is stamped.
        assert all(r["job"] == "lo" for r in lo_records)
        assert all(r["job"] == "hi" for r in hi_records)

        # The scheduler's scrape reflects the executed decisions.
        text = sched.metrics_text()
        parsed = hvd_metrics.validate_prometheus_text(text)
        actions = {l["action"]: v for l, v in
                   parsed["hvd_sched_decisions_total"]["samples"]}
        assert actions["shrink"] == 1.0
        assert actions["grant"] == 2.0
        assert actions["preempt"] == 0.0
        assert parsed["hvd_jobs_preempted_total"]["samples"] == [
            ({}, 0.0)]
