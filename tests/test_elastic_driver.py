"""Elastic driver tests, using the reference's fault-injection harness
pattern (``test/integration/elastic_common.py``): the discovery script reads
a file the test mutates; worker failures are induced via behavior flags; the
test asserts the world-version trajectory and recovery."""

import json
import os
import stat
import sys
import textwrap
import time

import pytest

from horovod_tpu.runner.elastic.discovery import (
    FixedHostDiscovery,
    HostDiscoveryScript,
    HostManager,
)
from horovod_tpu.runner.elastic.driver import run_elastic
from horovod_tpu.runner.hosts import HostInfo
from horovod_tpu.runner.launch import Settings

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Distinct names that all resolve to this machine — localhost-as-cluster.
LOCAL_ALIASES = ["localhost", "127.0.0.1"]


def _write_discovery(tmp_path, hosts: list[str]):
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("\n".join(hosts) + "\n")
    script = tmp_path / "discover.sh"
    script.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return str(script), hosts_file


class TestHostManager:
    def test_blacklist_and_pick(self):
        m = HostManager(FixedHostDiscovery([HostInfo("a", 1), HostInfo("b", 1)]))
        m.update_available_hosts()
        assert [h.hostname for h in m.usable_hosts()] == ["a", "b"]
        m.blacklist("a")
        assert [h.hostname for h in m.usable_hosts()] == ["b"]
        # preference keeps running hosts first; blacklisted never returns
        world = m.pick_world(["a", "b"], max_np=None)
        assert [h.hostname for h in world] == ["b"]

    def test_blacklist_cooldown_expiry_reports_change(self):
        """A cooldown expiry IS a usable-host-set change: the poll must
        return True so the driver reconfigures and re-admits the host —
        even with identical discovery output."""
        m = HostManager(
            FixedHostDiscovery([HostInfo("a", 1), HostInfo("b", 1)]),
            cooldown_s=0.2,
        )
        m.update_available_hosts()
        m.blacklist("a")
        assert m.is_blacklisted("a")
        # (Blacklist ADDITIONS are acted on directly by the driver's
        # failure path — the poll need not re-report them.)
        m.update_available_hosts()
        assert [h.hostname for h in m.usable_hosts()] == ["b"]
        assert m.update_available_hosts() is False  # steady state
        time.sleep(0.25)
        assert not m.is_blacklisted("a")            # cooldown expired
        assert m.update_available_hosts() is True   # a came BACK
        assert [h.hostname for h in m.usable_hosts()] == ["a", "b"]

    def test_blacklist_cooldown_readmission_under_churn(self):
        """Cooldown expiry racing a JOIN: host a fails and is blacklisted;
        while its cooldown runs out, a brand-new host c appears in
        discovery. The next poll must report a change (driving exactly one
        reconfiguration), and the next world must re-admit a AND admit c
        with stable ranks: the still-running host b keeps position 0, the
        returner and the joiner append behind it."""

        class MutableDiscovery(FixedHostDiscovery):
            def set_hosts(self, hosts):
                self._hosts = {h.hostname: h.slots for h in hosts}

        d = MutableDiscovery([HostInfo("a", 1), HostInfo("b", 1)])
        m = HostManager(d, cooldown_s=0.2)
        m.update_available_hosts()
        assert [h.hostname for h in m.pick_world([], None)] == ["a", "b"]

        m.blacklist("a")  # a's worker failed
        assert [h.hostname for h in m.pick_world(["a", "b"], None)] == ["b"]
        assert m.update_available_hosts() is False  # steady state, a banned

        # Churn: c joins discovery while a's cooldown expires.
        d.set_hosts([HostInfo("a", 1), HostInfo("b", 1), HostInfo("c", 1)])
        time.sleep(0.25)
        assert m.update_available_hosts() is True
        world = m.pick_world(["b"], max_np=None)
        assert [h.hostname for h in world] == ["b", "a", "c"]
        # And the change signal is edge-triggered: no further churn, no
        # further reconfigurations.
        assert m.update_available_hosts() is False

    def test_pick_world_stability_and_cap(self):
        m = HostManager(
            FixedHostDiscovery(
                [HostInfo("a", 1), HostInfo("b", 1), HostInfo("c", 1)]
            )
        )
        m.update_available_hosts()
        world = m.pick_world(["c", "b"], max_np=2)
        assert [h.hostname for h in world] == ["c", "b"]

    def test_valid_sizes_snap(self):
        # Topology constraint: only even world sizes are valid (e.g. paired
        # ICI hosts); 3 usable hosts must snap down to 2.
        m = HostManager(
            FixedHostDiscovery(
                [HostInfo("a", 1), HostInfo("b", 1), HostInfo("c", 1)]
            ),
            valid_sizes=lambda n: n % 2 == 0,
        )
        m.update_available_hosts()
        assert len(m.pick_world([], max_np=None)) == 2

    def test_discovery_script(self, tmp_path):
        script, hosts_file = _write_discovery(tmp_path, ["h1:2", "h2"])
        d = HostDiscoveryScript(script)
        assert d.find_available_hosts_and_slots() == {"h1": 2, "h2": 1}
        hosts_file.write_text("h1:2\n")
        assert d.find_available_hosts_and_slots() == {"h1": 2}


class TestHostManagerSpareTier:
    """HOROVOD_WARM_SPARES: surplus hosts held OUT of the world as warm
    standby, and its interaction with the blacklist cooldown — a
    just-condemned host proves itself warm before re-entering the world;
    a blacklisted host appears in neither tier."""

    def _manager(self, hosts=("a", "b", "c"), **kw):
        m = HostManager(
            FixedHostDiscovery([HostInfo(h, 1) for h in hosts]), **kw)
        m.update_available_hosts()
        return m

    def test_spares_held_out_of_world(self):
        m = self._manager(warm_spares=1)
        world = m.pick_world([], max_np=2)
        assert [h.hostname for h in world] == ["a", "b"]
        assert [h.hostname for h in m.spare_hosts()] == ["c"]
        assert m.warm_spares_target == 1

    def test_tier_disabled_is_head_behavior(self):
        m = self._manager(warm_spares=0)
        world = m.pick_world([], max_np=2)
        assert [h.hostname for h in world] == ["a", "b"]
        assert m.spare_hosts() == []
        assert m.warm_spares_target == 0

    def test_spare_backfills_world_immediately(self):
        """A world-member failure promotes the standby host into the
        world at the next pick — the one-re-rendezvous replacement."""
        m = self._manager(warm_spares=1, cooldown_s=60.0)
        m.pick_world([], max_np=2)                    # world [a,b], spare c
        m.blacklist("a")
        world = m.pick_world(["a", "b"], max_np=2)
        assert [h.hostname for h in world] == ["b", "c"]
        assert m.spare_hosts() == []                  # a is blacklisted

    def test_cooldown_returned_host_reenters_as_spare(self):
        """The satellite contract: a cooled-down host must re-enter as a
        SPARE, not swap straight back into a healthy full-size world."""
        m = self._manager(warm_spares=1, cooldown_s=0.2)
        m.pick_world([], max_np=2)
        m.blacklist("a")
        assert [h.hostname for h in m.pick_world(["a", "b"], max_np=2)] \
            == ["b", "c"]
        time.sleep(0.25)
        assert m.update_available_hosts() is True     # a came back
        world = m.pick_world(["b", "c"], max_np=2)
        assert [h.hostname for h in world] == ["b", "c"]   # world unchanged
        assert [h.hostname for h in m.spare_hosts()] == ["a"]

    def test_returned_spare_promoted_when_world_needs_it(self):
        """The probation flag clears exactly when the world would fall
        short without the host — which is the promotion path."""
        m = self._manager(warm_spares=1, cooldown_s=0.2)
        m.pick_world([], max_np=2)
        m.blacklist("a")
        m.pick_world(["a", "b"], max_np=2)            # world [b,c]
        time.sleep(0.25)
        m.update_available_hosts()
        m.pick_world(["b", "c"], max_np=2)            # a parked as spare
        m.blacklist("c")                              # now the world is short
        world = m.pick_world(["b", "c"], max_np=2)
        assert [h.hostname for h in world] == ["b", "a"]
        assert m.spare_hosts() == []

    def test_blacklisted_spare_never_promoted(self):
        """A blacklisted spare is not usable AT ALL: it must appear in
        neither the world nor the spare tier, even when the world is
        short."""
        m = self._manager(warm_spares=1, cooldown_s=60.0)
        m.pick_world([], max_np=2)                    # spare c
        m.blacklist("c")
        world = m.pick_world(["a", "b"], max_np=2)
        assert [h.hostname for h in world] == ["a", "b"]
        assert m.spare_hosts() == []
        m.blacklist("b")                              # world short of budget
        world = m.pick_world(["a", "b"], max_np=2)
        assert [h.hostname for h in world] == ["a"]   # c still banned
        assert m.spare_hosts() == []

    def test_departed_host_sheds_probation_flag(self):
        """A cooldown-returned host that then leaves discovery must not
        leak its probation flag back in when it reappears much later."""

        class MutableDiscovery(FixedHostDiscovery):
            def set_hosts(self, hosts):
                self._hosts = {h.hostname: h.slots for h in hosts}

        d = MutableDiscovery([HostInfo(h, 1) for h in ("a", "b", "c")])
        m = HostManager(d, warm_spares=1, cooldown_s=0.2)
        m.update_available_hosts()
        m.pick_world([], max_np=2)
        m.blacklist("a")
        m.pick_world(["a", "b"], max_np=2)
        time.sleep(0.25)
        m.update_available_hosts()
        m.pick_world(["b", "c"], max_np=2)
        assert [h.hostname for h in m.spare_hosts()] == ["a"]
        d.set_hosts([HostInfo("b", 1), HostInfo("c", 1)])   # a departs
        m.update_available_hosts()
        m.pick_world(["b", "c"], max_np=2)
        assert m.spare_hosts() == []
        assert "a" not in m._cooldown_returned


def _elastic_worker(tmp_path) -> str:
    """Worker driven by a behavior map {hostname: behavior}:
    - "fail_once": exit 1 on first launch, 0 on relaunch
    - "wait_for_version:N": poll the KV until world version >= N, print the
      assignment, exit 0 (exit 3 on timeout)
    """
    path = tmp_path / "elastic_worker.py"
    path.write_text(
        textwrap.dedent(
            f"""
            import json, os, sys, time
            sys.path.insert(0, {str(REPO_ROOT)!r})
            from horovod_tpu.runner.http.kv_server import KVClient

            host = os.environ["HOROVOD_HOSTNAME"]
            client = KVClient(os.environ["HOROVOD_RENDEZVOUS_ADDR"],
                              int(os.environ["HOROVOD_RENDEZVOUS_PORT"]))
            behavior = json.load(open(os.environ["TEST_BEHAVIOR_FILE"])).get(
                host, "wait_for_version:1")
            print("start host=%s v=%s behavior=%s" % (
                host, os.environ["HOROVOD_WORLD_VERSION"], behavior), flush=True)
            if behavior == "fail_once":
                marker = os.environ["TEST_TMP"] + "/failed_" + host
                if not os.path.exists(marker):
                    open(marker, "w").close()
                    sys.exit(1)
                sys.exit(0)
            target = int(behavior.split(":")[1])
            deadline = time.time() + 30
            while time.time() < deadline:
                v = client.world_version()
                if v >= target:
                    a = json.loads(client.get("world/%d" % v, host) or "{{}}")
                    print("host=%s sees v%d np=%s" % (
                        host, v, a.get("num_processes")), flush=True)
                    sys.exit(0)
                time.sleep(0.05)
            sys.exit(3)
            """
        )
    )
    return str(path)


def _settings(tmp_path, script, behavior: dict, min_np=1, max_np=None):
    behavior_file = tmp_path / "behavior.json"
    behavior_file.write_text(json.dumps(behavior))
    worker = _elastic_worker(tmp_path)
    return Settings(
        num_proc=1,
        hosts=[],
        command=[sys.executable, worker],
        cpu_mode=False,
        elastic=True,
        min_np=min_np,
        max_np=max_np,
        discovery_script=script,
        elastic_timeout=20.0,
        env={
            "TEST_BEHAVIOR_FILE": str(behavior_file),
            "TEST_TMP": str(tmp_path),
        },
    )


class TestElasticDriver:
    @pytest.mark.slow
    def test_completes_when_worker_exits_zero(self, tmp_path):
        script, _ = _write_discovery(tmp_path, ["localhost"])
        settings = _settings(
            tmp_path, script, {"localhost": "wait_for_version:1"}
        )
        lines: list[str] = []
        assert run_elastic(settings, sink=lines.append) == 0
        assert any("sees v1 np=1" in l for l in lines)

    @pytest.mark.slow
    def test_worker_failure_blacklists_and_recovers(self, tmp_path):
        # Two "hosts"; the first fails once. The driver must blacklist it,
        # re-form the world as {127.0.0.1} (v2), and the survivor finishes.
        script, _ = _write_discovery(tmp_path, LOCAL_ALIASES)
        settings = _settings(
            tmp_path,
            script,
            {"localhost": "fail_once", "127.0.0.1": "wait_for_version:2"},
            min_np=1,
        )
        lines: list[str] = []
        assert run_elastic(settings, sink=lines.append) == 0
        assert any("host=127.0.0.1 sees v2 np=1" in l for l in lines)

    @pytest.mark.slow
    def test_scale_up_on_host_added(self, tmp_path):
        # Start with one host; add a second mid-run by editing the hosts
        # file (the reference's fault-injection idiom). Workers wait for v2.
        script, hosts_file = _write_discovery(tmp_path, ["localhost"])
        settings = _settings(
            tmp_path,
            script,
            {
                "localhost": "wait_for_version:2",
                "127.0.0.1": "wait_for_version:2",
            },
        )
        lines: list[str] = []

        import threading

        def add_host():
            time.sleep(1.5)
            hosts_file.write_text("localhost\n127.0.0.1\n")

        t = threading.Thread(target=add_host)
        t.start()
        rc = run_elastic(settings, sink=lines.append)
        t.join()
        assert rc == 0
        assert any("sees v2 np=2" in l for l in lines)

    def test_times_out_below_min_np(self, tmp_path):
        script, _ = _write_discovery(tmp_path, ["localhost"])
        settings = _settings(
            tmp_path, script, {}, min_np=2
        )
        settings.elastic_timeout = 1.0
        with pytest.raises(TimeoutError):
            run_elastic(settings, sink=lambda s: None)


class TestTorchElasticE2E:
    """Full-stack elastic recovery on the torch surface: a worker dies
    mid-training; the survivor takes a HorovodInternalError in its next
    collective, restores the last TorchState commit, re-forms the world
    (new epoch, new native port from the KV), and finishes alone."""

    @pytest.mark.slow
    def test_worker_death_recovery_torch_state(self, tmp_path):
        worker = tmp_path / "torch_elastic_worker.py"
        worker.write_text(textwrap.dedent(f"""
            import os, sys, time
            sys.path.insert(0, {REPO_ROOT!r})
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            import torch
            import horovod_tpu as hvd_core
            import horovod_tpu.torch as hvd
            from horovod_tpu.elastic import run as elastic_run
            from horovod_tpu.torch.elastic import TorchState

            host = os.environ["HOROVOD_HOSTNAME"]
            tmp = os.environ["TEST_TMP"]

            torch.manual_seed(0)
            model = torch.nn.Linear(4, 1)
            opt = hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.05),
                named_parameters=model.named_parameters())
            state = TorchState(model=model, optimizer=opt, epoch=0)

            @elastic_run
            def train(state):
                while state.epoch < 5:
                    if (host == "localhost" and state.epoch == 2
                            and not os.path.exists(tmp + "/died")):
                        open(tmp + "/died", "w").close()
                        print("worker %s dying at epoch %d" % (
                            host, state.epoch), flush=True)
                        os._exit(1)
                    x = torch.from_numpy(np.random.RandomState(
                        state.epoch).randn(8, 4).astype(np.float32))
                    opt.zero_grad()
                    loss = (model(x) ** 2).mean()
                    loss.backward()
                    opt.step()
                    state.epoch += 1
                    state.commit()
                    print("host=%s epoch=%d np=%d loss=%.4f" % (
                        host, state.epoch, hvd.size(), float(loss)),
                        flush=True)
                return state.epoch

            done = train(state)
            print("host=%s finished at epoch %d" % (host, done), flush=True)
        """))
        script, _ = _write_discovery(tmp_path, LOCAL_ALIASES)
        settings = Settings(
            num_proc=2,
            hosts=[],
            command=[sys.executable, str(worker)],
            cpu_mode=True,
            elastic=True,
            min_np=1,
            max_np=2,
            discovery_script=script,
            elastic_timeout=30.0,
            env={"TEST_TMP": str(tmp_path)},
        )
        lines: list[str] = []
        rc = run_elastic(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("dying at epoch 2" in l for l in lines), lines
        assert any("finished at epoch 5" in l for l in lines), lines
        # The survivor ran some epochs in a 2-process world, then alone.
        assert any("np=2" in l for l in lines), lines
        assert any("host=127.0.0.1 epoch=5 np=1" in l for l in lines), lines


class TestGenerationRelaunchE2E:
    """VERDICT r4 #5 — the documented multi-host recovery path, driven
    by the REAL ElasticDriver: generation N (every worker) crashes at
    once; the blacklist cooldown returns the hosts; the driver publishes
    a new world version and relaunches generation N+1 as FRESH processes
    that re-init and resume from the last committed (on-disk) state.
    Loss continuity is asserted against an exact in-test replication of
    the averaged-SGD schedule — the resumed generation's losses must be
    the ones an uninterrupted run would have produced."""

    @pytest.mark.slow
    def test_generation_crash_relaunch_resumes(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN", "1.0")
        worker = tmp_path / "gen_worker.py"
        worker.write_text(textwrap.dedent(f"""
            import os, sys, time
            sys.path.insert(0, {REPO_ROOT!r})
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            import torch
            import horovod_tpu.torch as hvd
            from horovod_tpu.elastic import run as elastic_run
            from horovod_tpu.torch.elastic import TorchState

            host = os.environ["HOROVOD_HOSTNAME"]
            gen = os.environ.get("HOROVOD_WORLD_VERSION", "?")
            tmp = os.environ["TEST_TMP"]
            ckpt = tmp + "/ckpt.pt"

            torch.manual_seed(0)
            model = torch.nn.Linear(4, 1, bias=False)
            opt = hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.05),
                named_parameters=model.named_parameters())
            start_epoch = 0
            if os.path.exists(ckpt):
                saved = torch.load(ckpt)
                model.load_state_dict(saved["model"])
                start_epoch = saved["epoch"]
                print("gen=%s host=%s restored epoch=%d" % (
                    gen, host, start_epoch), flush=True)
            state = TorchState(model=model, optimizer=opt,
                               epoch=start_epoch)

            @elastic_run
            def train(state):
                while state.epoch < 5:
                    marker = tmp + "/died_" + host
                    if state.epoch == 2 and not os.path.exists(marker):
                        open(marker, "w").close()
                        print("gen=%s worker %s dying at epoch 2" % (
                            gen, host), flush=True)
                        os._exit(1)
                    r = hvd.rank()
                    x = torch.from_numpy(np.random.RandomState(
                        100 * state.epoch + r).randn(8, 4)
                        .astype(np.float32))
                    opt.zero_grad()
                    loss = (model(x) ** 2).mean()
                    loss.backward()
                    opt.step()
                    print("gen=%s rank=%d epoch=%d np=%d loss=%.6f" % (
                        gen, r, state.epoch, hvd.size(), float(loss)),
                        flush=True)
                    state.epoch += 1
                    state.commit()
                    if r == 0:
                        torch.save({{"model": model.state_dict(),
                                     "epoch": state.epoch}}, ckpt + ".tmp")
                        os.replace(ckpt + ".tmp", ckpt)
                return state.epoch

            done = train(state)
            print("gen=%s host=%s finished at epoch %d" % (
                gen, host, done), flush=True)
        """))
        script, _ = _write_discovery(tmp_path, LOCAL_ALIASES)
        settings = Settings(
            num_proc=2,
            hosts=[],
            command=[sys.executable, str(worker)],
            cpu_mode=True,
            elastic=True,
            min_np=2,          # the NEW generation must be full-size
            max_np=2,
            discovery_script=script,
            elastic_timeout=60.0,
            env={"TEST_TMP": str(tmp_path)},
        )
        lines: list[str] = []
        rc = run_elastic(settings, sink=lines.append)
        text = "\n".join(str(x) for x in lines)
        assert rc == 0, text
        # Both workers of generation N died together.
        assert text.count("dying at epoch 2") == 2, text
        # A LATER generation restored the committed state and finished.
        assert "restored epoch=2" in text, text
        assert "finished at epoch 5" in text, text
        gens = {int(m.split("=")[1].split()[0])
                for m in text.splitlines() if m.find("gen=") != -1
                for m in [m[m.find("gen="):]]}
        assert len(gens) >= 2, gens  # the world version advanced

        # Loss continuity: replicate the exact 2-rank averaged-SGD
        # schedule; the relaunched generation's per-rank losses at
        # epochs 2-4 must match what an uninterrupted run produces.
        import re

        import numpy as np
        import torch

        torch.manual_seed(0)
        m = torch.nn.Linear(4, 1, bias=False)
        sgd = torch.optim.SGD(m.parameters(), lr=0.05)
        expected = {}
        for e in range(5):
            grads = []
            for r in range(2):
                x = torch.from_numpy(np.random.RandomState(
                    100 * e + r).randn(8, 4).astype(np.float32))
                sgd.zero_grad()
                loss = (m(x) ** 2).mean()
                expected[(e, r)] = float(loss.detach())
                loss.backward()
                grads.append([p.grad.clone() for p in m.parameters()])
            with torch.no_grad():
                for p, g0, g1 in zip(m.parameters(), *grads):
                    p.grad = (g0 + g1) / 2
            sgd.step()
        seen = {}
        for line in text.splitlines():
            match = re.search(
                r"gen=(\d+) rank=(\d+) epoch=(\d+) np=2 "
                r"loss=([0-9.]+)", line)
            if match:
                g, r, e, l = (int(match.group(1)), int(match.group(2)),
                              int(match.group(3)), float(match.group(4)))
                seen[(e, r)] = (g, l)
        for e in range(5):
            for r in range(2):
                assert (e, r) in seen, (e, r, sorted(seen))
                _, got = seen[(e, r)]
                assert abs(got - expected[(e, r)]) < 1e-4, (
                    e, r, got, expected[(e, r)])
        # Epochs 2-4 ran in the relaunched generation.
        assert all(seen[(e, r)][0] > seen[(0, 0)][0]
                   for e in (2, 3, 4) for r in (0, 1)), seen


class TestTensorFlowElasticE2E:
    """Full-stack elastic recovery on the TF/Keras surface: a worker dies
    mid-training; the survivor takes a HorovodInternalError in its next
    collective, restores the last TensorFlowKerasState commit, re-forms
    the world, and finishes alone (mirror of TestTorchElasticE2E)."""

    @pytest.mark.slow
    def test_worker_death_recovery_keras_state(self, tmp_path):
        pytest.importorskip("tensorflow")
        worker = tmp_path / "tf_elastic_worker.py"
        worker.write_text(textwrap.dedent(f"""
            import os, sys
            sys.path.insert(0, {REPO_ROOT!r})
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            from horovod_tpu._jax_compat import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            import tensorflow as tf
            import horovod_tpu.keras as hvdk
            from horovod_tpu.elastic import run as elastic_run
            from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

            host = os.environ["HOROVOD_HOSTNAME"]
            tmp = os.environ["TEST_TMP"]

            tf.random.set_seed(0)
            model = tf.keras.Sequential(
                [tf.keras.layers.Dense(1, input_shape=(4,))])
            opt = hvdk.DistributedOptimizer(
                tf.keras.optimizers.SGD(learning_rate=0.05, momentum=0.9))
            state = TensorFlowKerasState(model=model, optimizer=opt,
                                         epoch=0)

            @elastic_run
            def train(state):
                while state.epoch < 5:
                    if (host == "localhost" and state.epoch == 2
                            and not os.path.exists(tmp + "/died")):
                        open(tmp + "/died", "w").close()
                        print("worker %s dying at epoch %d" % (
                            host, state.epoch), flush=True)
                        os._exit(1)
                    x = np.random.RandomState(
                        state.epoch).randn(8, 4).astype(np.float32)
                    with tf.GradientTape() as tape:
                        loss = tf.reduce_mean(model(tf.constant(x)) ** 2)
                    opt.apply_gradients(zip(
                        tape.gradient(loss, model.trainable_variables),
                        model.trainable_variables))
                    state.epoch += 1
                    state.commit()
                    print("host=%s epoch=%d np=%d loss=%.4f" % (
                        host, state.epoch, hvdk.size(), float(loss)),
                        flush=True)
                return state.epoch

            done = train(state)
            print("host=%s finished at epoch %d" % (host, done),
                  flush=True)
        """))
        script, _ = _write_discovery(tmp_path, LOCAL_ALIASES)
        settings = Settings(
            num_proc=2,
            hosts=[],
            command=[sys.executable, str(worker)],
            cpu_mode=True,
            elastic=True,
            min_np=1,
            max_np=2,
            discovery_script=script,
            elastic_timeout=30.0,
            env={"TEST_TMP": str(tmp_path)},
        )
        lines: list[str] = []
        rc = run_elastic(settings, sink=lines.append)
        assert rc == 0, "\n".join(lines)
        assert any("dying at epoch 2" in l for l in lines), lines
        assert any("finished at epoch 5" in l for l in lines), lines
        assert any("np=2" in l for l in lines), lines
        assert any("host=127.0.0.1 epoch=5 np=1" in l for l in lines), lines
