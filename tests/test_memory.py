"""HBM memory observatory tests (the PR-20 acceptance proof).

Layers, mirroring ``horovod_tpu/memory.py``'s model / measure / expose /
consume shape:

- **exactness**: ``predict_footprint`` / ``footprint_of`` priced against
  the MEASURED resident bytes of live state on the 8-device CPU mesh —
  monolithic / sharded / fsdp, 1-D and 2-D meshes, int8 on and off,
  uneven (non-divisible) and scalar leaves — byte-for-byte equality,
  not tolerance;
- **live accounting**: the call-site noting (shard_params, sharded
  optimizer init, executable cache), phase watermarks through real
  tracing spans, the top-leaves forensics table;
- **exposure**: the payload/merge contract (malformed-skip, rank
  collision, insufficient_samples) and the 2-worker ``GET /memory``
  HTTP merge e2e over the real heartbeat plumbing;
- **consumers**: the ``memory.pressure`` fault-injected OOM dumping a
  flight record that names the dominant leaf; the autotune memory
  guard's candidate pricing and SyncModeIneligibleError discipline;
  the scheduler's advisory admission check — each with an A/B arm
  proving the knob-unset path is bit-for-bit inert.
"""

import json
import urllib.request

import numpy as np
import pytest

from horovod_tpu import faults
from horovod_tpu import memory
from horovod_tpu import metrics as hvd_metrics
from horovod_tpu import tracing
from horovod_tpu.exceptions import (MemoryBudgetExceededError,
                                    SyncModeIneligibleError)


@pytest.fixture(autouse=True)
def _fresh_observatory():
    memory.reset_for_testing()
    faults.reset()
    yield
    memory.reset_for_testing()
    faults.reset()
    hvd_metrics.reset_for_testing()


def _init():
    import horovod_tpu as hvd

    hvd.init()
    return hvd


def _uneven_params():
    """Deliberately awkward leaves: a 10-element vector (ceil(10/8)=2,
    6 padding elements), a scalar, and a large divisible one."""
    import jax.numpy as jnp

    return {
        "w": jnp.arange(10, dtype=jnp.float32),
        "b": jnp.float32(0.5),
        "k": jnp.ones((1000,), jnp.float32),
    }


def _measured_resident(hvd, opt, params, mode, n):
    """The byte count the live layouts actually occupy per rank —
    measured from materialized state, independent of the model."""
    import jax

    from bench import _tree_bytes
    from horovod_tpu.parallel import param_sharding

    if mode == "allreduce":
        return (_tree_bytes(params)
                + _tree_bytes(jax.eval_shape(opt.init, params)))
    if mode == "sharded":
        return _tree_bytes(params) + _tree_bytes(opt.init(params)) // n
    sp = hvd.shard_params(params, n)
    return (param_sharding.resident_param_bytes(sp)
            + _tree_bytes(opt.init(params)) // n)


# ---------------------------------------------------------------------------
# Exactness: predicted == measured
# ---------------------------------------------------------------------------


class TestExactness:
    @pytest.mark.parametrize("mode", ["allreduce", "sharded", "fsdp"])
    @pytest.mark.parametrize("int8", [False, True])
    def test_predicted_equals_measured(self, mode, int8):
        """footprint_of prices the live layouts byte-for-byte, uneven
        and scalar leaves included, with and without the int8 salt."""
        import optax

        hvd = _init()
        n = hvd.size()
        params = _uneven_params()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1, momentum=0.9),
            compression=(hvd.Compression.int8 if int8
                         else hvd.Compression.none),
            sync_mode=mode)
        fp = memory.footprint_of(opt, params, world_size=n,
                                 sync_mode=mode)
        measured = _measured_resident(hvd, opt, params, mode, n)
        assert fp["resident_total"] == measured
        assert fp["opt_exact"] is True
        assert fp["int8"] is int8

    @pytest.mark.parametrize("int8", [False, True])
    def test_2d_mesh_resident_identical_to_1d(self, int8):
        """The ceil identity: fsdp resident bytes on any BxM
        factorization equal the flat rows exactly — and both equal the
        measured layout (resident rows keep the flat layout)."""
        import optax

        hvd = _init()
        n = hvd.size()
        params = _uneven_params()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1, momentum=0.9),
            compression=(hvd.Compression.int8 if int8
                         else hvd.Compression.none),
            sync_mode="fsdp")
        flat = memory.footprint_of(opt, params, world_size=n,
                                   sync_mode="fsdp")
        two_d = memory.footprint_of(opt, params, world_size=n,
                                    sync_mode="fsdp",
                                    mesh_shape=(n // 2, 2))
        measured = _measured_resident(hvd, opt, params, "fsdp", n)
        assert flat["resident_total"] == two_d["resident_total"] == measured
        # What the model axis DOES change: the transient gather legs.
        assert two_d["transient"]["model_axis_gather"] > 0
        assert flat["transient"]["model_axis_gather"] == 0

    def test_adam_scalar_count_leaf(self):
        """Adam's () count leaf rides the max(1, ceil) floor — the
        classic off-by-padding case a bytes-level model gets wrong."""
        import optax

        hvd = _init()
        n = hvd.size()
        params = _uneven_params()
        for mode in ("sharded", "fsdp"):
            opt = hvd.DistributedOptimizer(optax.adam(1e-3),
                                           sync_mode=mode)
            fp = memory.footprint_of(opt, params, world_size=n,
                                     sync_mode=mode)
            measured = _measured_resident(hvd, opt, params, mode, n)
            assert fp["resident_total"] == measured

    def test_element_counts_not_bytes(self):
        """Sharding prices ELEMENT counts: a 10-elem float32 leaf on 8
        ranks costs ceil(10/8)*4 = 8 bytes/rank, not ceil(40/8) = 5."""
        fp = memory.predict_footprint([(10, 4, "float32")],
                                      sync_mode="fsdp", world_size=8,
                                      opt_templates=[])
        assert fp["resident"]["params"] == 8

    def test_predict_footprint_is_jax_free(self):
        """The template-level entry prices from plain tuples (the
        stdlib path the scheduler and driver-side tools use)."""
        fp = memory.predict_footprint(
            [(1000, 4, "float32"), (1, 4, "float32")],
            sync_mode="sharded", world_size=8, opt_slots=2)
        # full params + 2 param-sized slots sharded per-leaf.
        assert fp["resident"]["params"] == 4004
        assert fp["resident"]["opt_state"] == 2 * (125 * 4 + 4)
        assert fp["opt_exact"] is False

    def test_transient_terms(self):
        leaves = [(1 << 20, 4, "float32")]
        fp = memory.predict_footprint(
            leaves, sync_mode="fsdp", world_size=8,
            threshold_bytes=1 << 20, num_segments=1,
            expert_set={"bytes": 512}, serving_staging=True)
        t = fp["transient"]
        assert t["fsdp_gather"] == 4 << 20      # the full segment
        assert t["moe_alltoall"] == 1024        # 2x explicit bytes
        assert t["serve_staging"] == 4 << 20    # a full staged replica
        assert t["grad_buckets"] > 0
        assert fp["peak_total"] == fp["resident_total"] + max(t.values())

    def test_capacity_headroom(self):
        base = memory.predict_footprint([(100, 4, "float32")],
                                        world_size=1, opt_templates=[])
        cap = 2 * base["peak_total"]
        fp = memory.predict_footprint([(100, 4, "float32")],
                                      world_size=1, opt_templates=[],
                                      capacity=cap)
        assert fp["capacity_bytes"] == cap
        assert fp["predicted_headroom_ratio"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# Live accounting
# ---------------------------------------------------------------------------


class TestLiveAccounting:
    def test_shard_params_notes_resident_and_leaves(self):
        hvd = _init()
        params = _uneven_params()
        hvd.shard_params(params, hvd.size())
        obs = memory.get_observatory()
        resident = obs.measured_resident()
        assert resident.get("params") == 512  # (2 + 1 + 125) * 4
        top = obs.top_leaves()
        assert top and top[0]["kind"] == "params"
        assert "k" in top[0]["leaf"]  # the 1000-elem leaf dominates

    def test_elastic_state_notes_sharded_opt_state(self):
        """TpuState registers the stacked sharded optimizer state at
        its exact per-rank bytes (total / world rows)."""
        import optax

        hvd = _init()
        params = _uneven_params()
        opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                       sync_mode="sharded")
        hvd.elastic.TpuState(params=params, opt_state=opt.init(params),
                             sharded_optimizer=opt)
        assert memory.get_observatory().measured_resident().get(
            "opt_state") == 512

    def test_executable_cache_bytes_flow(self):
        hvd = _init()
        n = hvd.size()
        before = hvd.cache_stats()["executable_cache"]
        hvd.allreduce(np.ones((n, 4), np.float32), op=hvd.Sum)
        stats = hvd.cache_stats()["executable_cache"]
        assert "bytes" in before
        assert stats["bytes"] > 0
        assert memory.get_observatory().measured_resident().get(
            "executables") == stats["bytes"]
        from horovod_tpu.ops.executable_cache import global_cache

        global_cache().clear()
        assert hvd.cache_stats()["executable_cache"]["bytes"] == 0

    def test_phase_watermarks_through_spans(self):
        memory.note_resident("params", 1000)
        tracing.reset_for_testing()
        with tracing.span("forward_backward", "compute"):
            pass
        memory.note_resident("params", 4000)
        with tracing.span("optimizer_update", "compute"):
            pass
        marks = memory.get_observatory().watermarks()
        assert marks["forward_backward"] >= 1000
        assert marks["optimizer_update"] >= 4000
        assert memory.get_observatory().peak_bytes() >= 4000
        # Gauge side: the phase cell carries the watermark.
        assert hvd_metrics.HBM_WATERMARK.labels(
            phase="optimizer_update").get() >= 4000

    def test_summary_and_profiler_surface(self):
        memory.note_resident("params", 2048,
                             top_leaves=[("w", 2048)])
        s = memory.summary()
        assert s["status"] == "ok"
        assert s["resident"]["params"] == 2048
        assert s["top_leaves"][0]["leaf"] == "w"
        from horovod_tpu import profiler

        assert profiler.summary()["memory"]["resident"]["params"] == 2048

    def test_flight_summary_none_when_cold(self):
        assert memory.flight_summary() is None
        memory.note_resident("params", 1)
        assert memory.flight_summary()["resident"]["params"] == 1


# ---------------------------------------------------------------------------
# Exposure: merge + GET /memory
# ---------------------------------------------------------------------------


def _payload(rank, host, **over):
    p = {"rank": rank, "host": host, "t": 1.0, "status": "ok",
         "resident": {"params": 100 * (rank + 1), "opt_state": 10},
         "resident_total": 100 * (rank + 1) + 10,
         "watermarks": {"step": 500 * (rank + 1)},
         "peak_bytes": 500 * (rank + 1),
         "headroom_ratio": 0.9 - rank * 0.5,
         "residual_bytes": (-3) ** rank,
         "capacity_bytes": 10000}
    p.update(over)
    return p


class TestMergePayloads:
    def test_cluster_aggregates(self):
        merged = memory.merge_payloads({
            "host-a": _payload(0, "host-a"),
            "host-b": _payload(1, "host-b"),
        })
        assert merged["status"] == "ok"
        assert len(merged["ranks"]) == 2
        c = merged["cluster"]
        assert c["resident_bytes"]["params"] == 300     # sums
        assert c["resident_total"] == 320
        assert c["watermark_bytes"]["step"] == 1000     # max
        assert c["headroom_ratio_min"] == pytest.approx(0.4)
        assert c["residual_bytes_worst"] == -3          # largest |.|

    def test_malformed_skipped_and_collision_keyed(self):
        merged = memory.merge_payloads({
            "host-a": _payload(0, "host-a"),
            "host-b": {"garbage": True},        # dict: kept, degraded
            "host-c": ["not", "a", "dict"],     # non-mapping: skipped
            "host-d": _payload(0, "host-d"),    # rank collision
        })
        assert merged["status"] == "ok"
        keys = set(merged["ranks"])
        assert keys == {"0", "0@host-d", "?"}
        # The degraded entry must not poison the cluster sums (both
        # surviving payloads are rank-0 shaped: 100 bytes each).
        assert merged["ranks"]["?"]["status"] == "insufficient_samples"
        assert merged["cluster"]["resident_bytes"]["params"] == 200

    def test_empty_is_insufficient_samples(self):
        assert memory.merge_payloads({})["status"] == "insufficient_samples"

    def test_nonfinite_rejected(self):
        merged = memory.merge_payloads({
            "host-a": _payload(0, "host-a",
                               resident={"params": float("nan")},
                               peak_bytes=float("inf"))})
        r = merged["ranks"]["0"]
        assert r["resident"].get("params", 0) == 0
        assert r["peak_bytes"] == 0
        json.dumps(merged)  # must stay JSON-serializable


class TestMemoryEndpoint:
    def _server(self):
        from horovod_tpu.runner.http.kv_server import RendezvousServer

        srv = RendezvousServer(host="127.0.0.1")
        srv.start()
        return srv

    def test_get_memory_merges_two_ranks(self):
        from horovod_tpu.runner.http.kv_server import KVClient

        srv = self._server()
        try:
            client = KVClient("127.0.0.1", srv.port)
            for rank, host in ((0, "mem-r0"), (1, "mem-r1")):
                client.put("heartbeat", host, json.dumps(
                    {"rank": rank, "steps": 1, "commits": 0,
                     "memory": _payload(rank, host)}).encode())
            url = f"http://127.0.0.1:{srv.port}/memory"
            with urllib.request.urlopen(url, timeout=10) as r:
                assert r.status == 200
                body = json.loads(r.read())
            assert body["status"] == "ok"
            assert len(body["ranks"]) == 2
            assert body["cluster"]["resident_bytes"]["params"] == 300
            assert body["generation"] == srv.version
        finally:
            srv.stop()

    def test_cold_server_insufficient_samples_not_500(self):
        srv = self._server()
        try:
            url = f"http://127.0.0.1:{srv.port}/memory"
            with urllib.request.urlopen(url, timeout=10) as r:
                assert r.status == 200
                body = json.loads(r.read())
            assert body["status"] == "insufficient_samples"
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Consumer: OOM forensics
# ---------------------------------------------------------------------------


class TestOomForensics:
    def test_is_oom_error_markers(self):
        assert memory.is_oom_error(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating"))
        assert memory.is_oom_error(
            RuntimeError("Failed to allocate 2.5G for buffer"))
        assert not memory.is_oom_error(ValueError("plenty of room"))
        assert not memory.is_oom_error(ValueError("blooming gardens"))

    def test_injected_pressure_dumps_flight_record_naming_leaf(
            self, tmp_path, monkeypatch):
        """The acceptance e2e: arm memory.pressure, run a real watched
        factory step on the 8-dev mesh, and the dumped flight record
        names the dominant resident leaf."""
        import optax

        ev = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
        hvd = _init()
        tracing.reset_for_testing()
        params = _uneven_params()
        hvd.shard_params(params, hvd.size())  # notes the leaf table

        def loss_fn(p, batch):
            import jax.numpy as jnp

            return jnp.mean((p["k"][:4] - batch) ** 2)

        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        step = hvd.data_parallel.make_train_step(loss_fn, opt,
                                                 donate=False)
        p = hvd.data_parallel.replicate(params)
        s = hvd.data_parallel.replicate(opt.init(params))
        batch = hvd.data_parallel.shard_batch(
            np.zeros((hvd.size() * 2, 4), np.float32))
        faults.inject(faults.MEMORY_PRESSURE, "drop", at=2)
        p, s, _ = step(p, s, batch)  # step 1: clean
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            step(p, s, batch)  # step 2: injected OOM at the boundary
        frs = [json.loads(l) for l in ev.read_text().splitlines()
               if json.loads(l)["event"] == "flight_record"]
        assert len(frs) == 1
        fr = frs[0]
        assert fr["reason"] == "oom"
        assert "memory.pressure" in fr["error"]
        top = fr["memory_top_leaves"]
        assert top and "k" in top[0]["leaf"]  # the dominant leaf, named
        assert fr["memory_resident"]["params"] == 512
        # Satellite: EVERY flight record carries the memory section.
        assert fr["memory"]["resident"]["params"] == 512
        monkeypatch.delenv("HOROVOD_EVENT_LOG")
        hvd_metrics.journal()

    def test_every_flight_record_attaches_memory(self, tmp_path,
                                                 monkeypatch):
        ev = tmp_path / "events.jsonl"
        monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
        memory.note_resident("params", 777)
        tracing.dump_flight_record("stall_shutdown")
        fr = [json.loads(l) for l in ev.read_text().splitlines()
              if json.loads(l)["event"] == "flight_record"][0]
        assert fr["memory"]["resident"]["params"] == 777
        monkeypatch.delenv("HOROVOD_EVENT_LOG")
        hvd_metrics.journal()


# ---------------------------------------------------------------------------
# Consumer: the autotune memory guard
# ---------------------------------------------------------------------------


class TestAutotuneGuard:
    LAYOUT = [(1 << 20, 4, "float32")]  # 4 MB of float32 params

    def _note_layout(self):
        memory.get_observatory().note_layout(self.LAYOUT)

    def _mid_capacity(self):
        """A budget strictly between the fsdp peak and the cheapest
        monolithic-params peak: fsdp fits, the other two do not."""
        peaks = {m: memory.predict_footprint(
            self.LAYOUT, sync_mode=m, world_size=8)["peak_total"]
            for m in ("allreduce", "sharded", "fsdp")}
        assert peaks["fsdp"] < min(peaks["allreduce"], peaks["sharded"])
        return (peaks["fsdp"]
                + min(peaks["allreduce"], peaks["sharded"])) // 2

    def test_inert_when_unset(self, monkeypatch):
        """A/B: with the knob unset the guard prices nothing and
        filters nothing, capacity or not."""
        monkeypatch.delenv("HOROVOD_AUTOTUNE_MEMORY_GUARD",
                           raising=False)
        monkeypatch.setenv("HOROVOD_HBM_BYTES_PER_DEVICE", "1")
        self._note_layout()
        assert memory.check_candidate("allreduce") is None
        cands = [(1 << 20, "allreduce"), (1 << 20, "fsdp")]
        verdict = memory.filter_candidates(cands, world_size=8)
        assert verdict["kept"] == cands
        assert verdict["pruned"] == []

    def test_check_candidate_raises_ineligible(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOTUNE_MEMORY_GUARD", "1")
        monkeypatch.setenv("HOROVOD_HBM_BYTES_PER_DEVICE",
                           str(self._mid_capacity()))
        monkeypatch.setenv("HOROVOD_SIZE", "8")
        self._note_layout()
        with pytest.raises(MemoryBudgetExceededError) as ei:
            memory.check_candidate("allreduce")
        assert isinstance(ei.value, SyncModeIneligibleError)
        assert memory.check_candidate("fsdp") is None  # fits

    def test_cold_or_capacityless_guard_is_inert(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOTUNE_MEMORY_GUARD", "1")
        monkeypatch.setenv("HOROVOD_SIZE", "8")
        # Armed but no layout noted: prunes nothing.
        monkeypatch.setenv("HOROVOD_HBM_BYTES_PER_DEVICE", "1")
        assert memory.check_candidate("allreduce") is None
        # Armed, layout noted, but no capacity source: prunes nothing.
        monkeypatch.delenv("HOROVOD_HBM_BYTES_PER_DEVICE")
        self._note_layout()
        assert memory.check_candidate("allreduce") is None

    def test_filter_candidates_never_prunes_whole_grid(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOTUNE_MEMORY_GUARD", "1")
        monkeypatch.setenv("HOROVOD_HBM_BYTES_PER_DEVICE", "1")
        monkeypatch.setenv("HOROVOD_SIZE", "8")
        self._note_layout()
        cands = [(1 << 20, "allreduce"), (1 << 20, "fsdp")]
        verdict = memory.filter_candidates(cands, world_size=8)
        assert verdict["kept"] == cands  # everything over: keep all
        monkeypatch.setenv("HOROVOD_HBM_BYTES_PER_DEVICE",
                           str(self._mid_capacity()))
        verdict = memory.filter_candidates(cands, world_size=8)
        assert verdict["kept"] == [(1 << 20, "fsdp")]
        assert verdict["pruned"] == [(1 << 20, "allreduce")]
        assert all(b is not None for b in verdict["bytes"])

    def test_tune_step_sync_mode_skips_over_budget(self, monkeypatch):
        """The sweep harness prices candidates before building them:
        over-budget modes skip rank-identically and the winner comes
        from the eligible ones."""
        from horovod_tpu import autotune

        monkeypatch.setenv("HOROVOD_AUTOTUNE_MEMORY_GUARD", "1")
        monkeypatch.setenv("HOROVOD_HBM_BYTES_PER_DEVICE",
                           str(self._mid_capacity()))
        monkeypatch.setenv("HOROVOD_SIZE", "8")
        _init()
        self._note_layout()
        built = []

        def build_step(mode):
            built.append(mode)
            import jax.numpy as jnp

            return lambda: jnp.zeros(())

        try:
            best = autotune.tune_step_sync_mode(
                build_step, sync_modes=("allreduce", "sharded", "fsdp"),
                iters=1)
            assert best == "fsdp"
            assert built == ["fsdp"]  # over-budget modes never built
        finally:
            autotune.set_tuned_sync_mode(None)


# ---------------------------------------------------------------------------
# Consumer: scheduler admission (advisory)
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_admission_check_math(self):
        assert memory.admission_check(None, 100) is None
        assert memory.admission_check(100, None) is None
        assert memory.admission_check(80, 100) is None
        risk = memory.admission_check(150, 100)
        assert risk == {"predicted_bytes": 150, "capacity_bytes": 100,
                        "deficit_bytes": 50, "ratio": 1.5}

    def test_admission_check_garbage_is_none(self):
        assert memory.admission_check("junk", 100) is None
        assert memory.admission_check(-5, 100) is None

    def test_scheduler_grant_journals_risk_and_stays_advisory(
            self, tmp_path, monkeypatch):
        """A granted job with a declared over-capacity footprint
        journals admission_memory_risk — and is still granted. With
        the knobs unset, no event and the identical grant."""
        from horovod_tpu.runner.elastic.scheduler import (
            JobSpec, MultiJobScheduler)

        for arm, env in (("off", {}),
                         ("on", {"HOROVOD_HBM_PREDICTED_BYTES": "200"})):
            ev = tmp_path / f"events-{arm}.jsonl"
            monkeypatch.setenv("HOROVOD_EVENT_LOG", str(ev))
            if arm == "on":
                monkeypatch.setenv("HOROVOD_SCHED_HOST_HBM_BYTES", "100")
            else:
                monkeypatch.delenv("HOROVOD_SCHED_HOST_HBM_BYTES",
                                   raising=False)
            sched = MultiJobScheduler(
                [JobSpec(job_id=f"job-{arm}", command=["true"],
                         min_np=1, max_np=1, env=dict(env))],
                ["h1"], str(tmp_path / f"wd-{arm}"))
            monkeypatch.setattr(sched, "_launch_driver",
                                lambda job: None)
            sched._grant_pending()
            job = sched._jobs[f"job-{arm}"]
            assert job.lease == ["h1"]  # granted either way
            events = [json.loads(l) for l in ev.read_text().splitlines()
                      if l.strip()] if ev.exists() else []
            risks = [e for e in events
                     if e["event"] == "admission_memory_risk"]
            if arm == "on":
                assert len(risks) == 1
                assert risks[0]["deficit_bytes"] == 100
                assert risks[0]["job"] == "job-on"
            else:
                assert risks == []
            monkeypatch.delenv("HOROVOD_EVENT_LOG")
            hvd_metrics.journal()


# ---------------------------------------------------------------------------
# Gauges
# ---------------------------------------------------------------------------


class TestGauges:
    def test_zero_materialized_cells(self):
        text = hvd_metrics.render()
        for fam in ("hvd_hbm_bytes", "hvd_hbm_watermark_bytes",
                    "hvd_hbm_headroom_ratio",
                    "hvd_hbm_model_residual_bytes"):
            assert fam in text
        for kind in memory.KINDS:
            assert f'hvd_hbm_bytes{{kind="{kind}"}}' in text

    def test_note_resident_sets_kind_gauge(self):
        memory.note_resident("serving", 4096)
        assert hvd_metrics.HBM_BYTES.labels(kind="serving").get() == 4096

    def test_headroom_gauge_with_capacity(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_HBM_BYTES_PER_DEVICE", "1000")
        memory.note_resident("params", 250)
        assert memory.get_observatory().headroom_ratio() == \
            pytest.approx(0.75)
