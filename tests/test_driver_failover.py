"""Control-plane fault tolerance: driver crash-restart takeover with
split-brain fencing.

The chaos battery for `runner/elastic/driver_state.py` and the takeover
machinery around it:

- the durable snapshot store (atomic rotation, checksum verification,
  SIGKILL-mid-write falling back to the previous epoch's intact state)
- driver-epoch fencing at every layer: the state dir (a stale driver's
  snapshot/endpoint writes raise ``DriverFencedError``), the rendezvous
  KV (stale-epoch writes 409), and the worker (follows the highest
  epoch it has seen)
- the worker orphan loop: driver loss no longer exits 203 when the
  state plane is armed — the worker re-resolves the endpoint record and
  repoints every client at the successor
- end to end with the real ``ElasticDriver``: SIGKILL the driver
  mid-training with 2 workers → a supervisor relaunch resumes from the
  snapshot, both workers rejoin at generation g+1 WITHOUT a process
  restart, recovery lands on the peer rung (zero durable reads), and
  the loss trajectory matches an uninterrupted run step for step; plus
  the SIGSTOP'd-through-takeover stale driver standing down
  (``EXIT_DRIVER_SUPERSEDED``) without touching the successor's world.

Determinism contract: failures are injected (SIGKILL/SIGSTOP at exact
observed points, fault points on exact hits), so the tests assert exact
trajectories instead of racing a scheduler."""

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu import faults
from horovod_tpu.runner.elastic import driver_state
from horovod_tpu.runner.elastic.constants import (
    EXIT_DRIVER_SUPERSEDED,
)
from horovod_tpu.runner.http.kv_server import (
    KVClient,
    RendezvousServer,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_planes(monkeypatch):
    monkeypatch.delenv(faults.ENV_SPEC, raising=False)
    monkeypatch.delenv(driver_state.ENV_STATE_DIR, raising=False)
    monkeypatch.delenv(driver_state.ENV_DRIVER_EPOCH, raising=False)
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def kv_server():
    server = RendezvousServer()
    server.start()
    yield server
    server.stop()


# -- the snapshot store -------------------------------------------------------


class TestDriverStateStore:
    def test_save_load_roundtrip_and_epoch_monotonicity(self, tmp_path):
        d = str(tmp_path)
        store, snap = driver_state.DriverStateStore.open(d)
        assert snap is None and store.epoch == 1
        store.save({"generation": 3, "world": [["a", 1], ["b", 1]]})
        store2, snap2 = driver_state.DriverStateStore.open(d)
        assert store2.epoch == 2
        assert snap2["generation"] == 3
        assert snap2["world"] == [["a", 1], ["b", 1]]
        assert snap2["driver_epoch"] == 1

    def test_stale_driver_snapshot_and_endpoint_fenced(self, tmp_path):
        d = str(tmp_path)
        old, _ = driver_state.DriverStateStore.open(d)
        old.save({"generation": 1})
        new, _ = driver_state.DriverStateStore.open(d)
        new.save({"generation": 2})
        with pytest.raises(driver_state.DriverFencedError):
            old.save({"generation": 99})
        # The endpoint record is fenced against the SNAPSHOT's epoch
        # too (a successor may write either file first).
        with pytest.raises(driver_state.DriverFencedError):
            old.publish_endpoint("127.0.0.1", 1, 1)
        # The successor is unaffected, and its own records land.
        new.publish_endpoint("127.0.0.1", 4242, 2)
        rec = driver_state.read_endpoint(d)
        assert rec["driver_epoch"] == 2 and rec["port"] == 4242

    def test_open_clears_endpoint_epoch_too(self, tmp_path):
        # Crash between the endpoint write and the snapshot write can
        # leave the endpoint record at a HIGHER epoch than the snapshot;
        # the next open must clear both.
        d = str(tmp_path)
        store = driver_state.DriverStateStore(d, epoch=7)
        store.publish_endpoint("127.0.0.1", 1, 0)
        nxt, snap = driver_state.DriverStateStore.open(d)
        assert snap is None and nxt.epoch == 8

    def test_corrupt_current_falls_back_to_prev(self, tmp_path):
        d = str(tmp_path)
        store, _ = driver_state.DriverStateStore.open(d)
        store.save({"generation": 1, "tag": "good"})
        store.save({"generation": 2, "tag": "newer"})
        # Bit-rot the current slot: load must recover the retained one.
        path = store.state_path
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[: len(blob) // 2])
        rec = store.load()
        assert rec is not None and rec["tag"] == "good"

    def test_snapshot_fault_point(self, tmp_path):
        store, _ = driver_state.DriverStateStore.open(str(tmp_path))
        faults.inject(faults.DRIVER_SNAPSHOT, "raise", at=1, count=1)
        with pytest.raises(faults.InjectedFault):
            store.save({"generation": 1})
        store.save({"generation": 1})  # next attempt lands
        assert store.load()["generation"] == 1

    def test_read_endpoint_rejects_malformed(self, tmp_path, monkeypatch):
        d = str(tmp_path)
        monkeypatch.setenv(driver_state.ENV_STATE_DIR, d)
        assert driver_state.read_endpoint() is None  # nothing yet
        store = driver_state.DriverStateStore(d, epoch=1)
        store._fenced_install(store.endpoint_path, {"addr": "x"})  # no port
        assert driver_state.read_endpoint() is None

    def test_concurrent_opens_claim_distinct_epochs(self, tmp_path):
        """A flapping supervisor can relaunch two takeover drivers in
        the same window: the O_EXCL epoch claim must hand them DISTINCT
        epochs (equal epochs would pass every fence — split brain)."""
        d = str(tmp_path)
        a, _ = driver_state.DriverStateStore.open(d)
        b, _ = driver_state.DriverStateStore.open(d)
        assert a.epoch != b.epoch
        assert {a.epoch, b.epoch} == {1, 2}
        # The loser fences the winner out on its first write.
        b.save({"generation": 0})
        with pytest.raises(driver_state.DriverFencedError):
            a.save({"generation": 0})
        # A third open clears every claimed epoch, records or not.
        c, _ = driver_state.DriverStateStore.open(d)
        assert c.epoch == 3

    def test_proc_start_ticks_detects_pid_identity(self):
        ticks = driver_state.proc_start_ticks(os.getpid())
        assert ticks is not None and ticks > 0
        assert driver_state.proc_start_ticks(os.getpid()) == ticks
        # A vanished pid reads as None (callers fall back to pid-only).
        assert driver_state.proc_start_ticks(2 ** 22 + 12345) is None

    def test_adoption_rejects_recycled_pid(self, tmp_path, monkeypatch):
        """A snapshot PID alive but with a DIFFERENT kernel start time
        is a recycled PID naming a stranger — adoption must skip it
        (the liveness plane would otherwise SIGKILL an innocent
        process group)."""
        from horovod_tpu.runner.elastic.driver import ElasticDriver
        from horovod_tpu.runner.launch import Settings

        monkeypatch.setenv("HOROVOD_SECRET_KEY", "")
        monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
        settings = Settings(
            num_proc=1, hosts=[], command=["true"], elastic=True,
            min_np=1, max_np=1, discovery_script=None)
        from horovod_tpu.runner.elastic.discovery import (
            FixedHostDiscovery,
        )
        from horovod_tpu.runner.hosts import HostInfo

        drv = ElasticDriver(
            settings,
            discovery=FixedHostDiscovery([HostInfo("localhost", 1)]))
        me = os.getpid()
        good_ticks = driver_state.proc_start_ticks(me)
        adopted = drv._adopt_from_snapshot({"workers": {"localhost": {
            "pid": me, "local": True, "start_ticks": good_ticks - 7}}})
        assert adopted == [] and not drv._workers
        adopted = drv._adopt_from_snapshot({"workers": {"localhost": {
            "pid": me, "local": True, "start_ticks": good_ticks}}})
        assert adopted == ["localhost"] and "localhost" in drv._workers


class TestTornSnapshotChaos:
    def test_sigkill_mid_snapshot_restart_loads_previous_epoch(
            self, tmp_path):
        """The torn-write chaos case (mirrors test_peercheck's raw-socket
        pattern): a driver SIGKILLed mid-snapshot-write leaves a partial
        tmp file and/or a half-written current slot — the restarted
        driver must load the previous epoch's INTACT state, never a
        torn one, and take over at a strictly higher epoch."""
        script = tmp_path / "torn_driver.py"
        script.write_text(f"""
import os, signal, sys
sys.path.insert(0, {REPO_ROOT!r})
from horovod_tpu.runner.elastic import driver_state

d = os.environ["STATE_DIR"]
store, _ = driver_state.DriverStateStore.open(d)
store.save({{"generation": 5, "world": [["a", 1], ["b", 1]],
             "tag": "intact"}})
print("GOOD SAVED", flush=True)
# Next snapshot: die mid-write. Write half of a VALID next record
# straight into the current slot (the torn-filesystem case atomic
# rotation + checksums exist for), then SIGKILL.
blob = driver_state._encode({{"generation": 6, "tag": "torn",
                              "driver_epoch": store.epoch}})
# Rotate like atomic_install would have (prev = the good record)...
import shutil
shutil.copy(store.state_path, store.state_path + ".prev")
with open(store.state_path, "wb") as f:
    f.write(blob[: len(blob) // 2])
    f.flush()
    print("HALF WRITTEN", flush=True)
    os.kill(os.getpid(), signal.SIGKILL)
""")
        env = dict(os.environ)
        env["STATE_DIR"] = str(tmp_path / "state")
        proc = subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == -signal.SIGKILL, (proc.returncode, out)
        assert "HALF WRITTEN" in out, out
        # The restarted driver: loads the intact epoch-1 snapshot from
        # the retained slot, takes over at epoch 2.
        store, snap = driver_state.DriverStateStore.open(
            str(tmp_path / "state"))
        assert snap is not None, "takeover lost the snapshot entirely"
        assert snap["tag"] == "intact" and snap["generation"] == 5
        assert store.epoch == 2

    def test_sigkill_mid_tmp_write_leaves_current_untouched(
            self, tmp_path):
        """The atomic_install crash window proper: dying inside the tmp
        write must leave the CURRENT slot byte-identical."""
        script = tmp_path / "tmp_torn.py"
        script.write_text(f"""
import os, signal, sys
sys.path.insert(0, {REPO_ROOT!r})
from horovod_tpu.runner.elastic import driver_state

d = os.environ["STATE_DIR"]
store, _ = driver_state.DriverStateStore.open(d)
store.save({{"generation": 5, "tag": "intact"}})
print("GOOD SAVED", flush=True)
with open(store.state_path + ".tmp", "wb") as f:
    f.write(b"x" * 10)
    f.flush()
    os.kill(os.getpid(), signal.SIGKILL)
""")
        env = dict(os.environ)
        env["STATE_DIR"] = str(tmp_path / "state")
        proc = subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == -signal.SIGKILL, (proc.returncode, out)
        store, snap = driver_state.DriverStateStore.open(
            str(tmp_path / "state"))
        assert snap["tag"] == "intact" and store.epoch == 2


# -- KV-layer split-brain fencing --------------------------------------------


class TestDriverEpochFence:
    def test_stale_epoch_write_409_fresh_epoch_lands(self, kv_server):
        from urllib.error import HTTPError

        kv_server.seed(generation=5, driver_epoch=3)
        ok = KVClient("127.0.0.1", kv_server.port,
                      generation_fn=lambda: 5, epoch_fn=lambda: 3)
        ok.put("s", "k", b"v")
        stale = KVClient("127.0.0.1", kv_server.port,
                         generation_fn=lambda: 5, epoch_fn=lambda: 2)
        with pytest.raises(HTTPError) as ei:
            stale.put("s", "k", b"zombie")
        assert ei.value.code == 409
        assert ok.get("s", "k") == b"v"  # the zombie corrupted nothing
        assert kv_server.fenced_writes == 1
        assert ok.driver_epoch() == 3

    def test_headerless_writes_unfenced(self, kv_server):
        kv_server.seed(driver_epoch=9)
        plain = KVClient("127.0.0.1", kv_server.port)
        plain.put("s", "k", b"v")  # static/plain tooling keeps working
        assert plain.get("s", "k") == b"v"

    def test_epoch_only_writes_are_fenced_too(self, kv_server):
        """abort.post's client stamps the epoch WITHOUT a generation
        header — the epoch fence must still evaluate (a worker still
        loyal to a superseded driver cannot plant abort records)."""
        from urllib.error import HTTPError

        kv_server.seed(driver_epoch=5)
        stale = KVClient("127.0.0.1", kv_server.port, epoch_fn=lambda: 4)
        with pytest.raises(HTTPError) as ei:
            stale.put("abort", "3", b"{}")
        assert ei.value.code == 409
        fresh = KVClient("127.0.0.1", kv_server.port, epoch_fn=lambda: 5)
        fresh.put("abort", "3", b"{}")  # current epoch lands

    def test_seed_driver_lost_resumes_scrape_counts(self, kv_server):
        from horovod_tpu import metrics

        kv_server.seed_driver_lost({"hostA": 2, "hostB": "bad"})
        kv_server.record_driver_lost("hostA")
        parsed = metrics.validate_prometheus_text(
            kv_server.metrics_text())
        samples = dict(
            (tuple(sorted(l.items())), v)
            for l, v in parsed["hvd_driver_lost_total"]["samples"])
        assert samples[(("host", "hostA"),)] == 3.0
        assert samples[()] == 3.0

    def test_scrape_carries_epoch_and_driver_lost(self, kv_server):
        from horovod_tpu import metrics

        kv_server.seed(driver_epoch=4)
        kv_server.record_driver_lost("hostA")
        kv_server.record_driver_lost("hostA")
        text = kv_server.metrics_text()
        parsed = metrics.validate_prometheus_text(text)
        assert ({}, 4.0) in [
            (l, v) for l, v in parsed["hvd_driver_epoch"]["samples"]]
        samples = dict(
            (tuple(sorted(l.items())), v)
            for l, v in parsed["hvd_driver_lost_total"]["samples"])
        assert samples[()] == 2.0  # the zero-materialized total
        assert samples[(("host", "hostA"),)] == 2.0

    def test_kv_serve_fault_is_a_transport_failure(self, kv_server):
        client = KVClient("127.0.0.1", kv_server.port, retries=3,
                          backoff=0.01)
        faults.inject(faults.KV_SERVE, "drop", at=1, count=1)
        client.put("s", "k", b"v")  # dropped serve, retried, landed
        assert client.get("s", "k") == b"v"
        assert faults.fired(faults.KV_SERVE) == 1

    def test_done_records_roundtrip(self, kv_server):
        client = KVClient("127.0.0.1", kv_server.port)
        client.put("done", "hostA", json.dumps({"rc": 0}).encode())
        assert "hostA" in kv_server.done_records()


# -- policy/blacklist resume --------------------------------------------------


class TestControlPlaneResume:
    def test_policy_evidence_roundtrip(self):
        from horovod_tpu.elastic.policy import PolicyController

        clock = [100.0]
        a = PolicyController(clock=lambda: clock[0])
        a._ewma["h1"] = 1.5
        a._hb_ewma["h1"] = 0.4
        a._above_since["h1"] = 70.0  # condemned for 30s
        a.note_resize_cost(12.0)
        state = a.export_state()
        clock[0] = 500.0  # a different clock era entirely
        b = PolicyController(clock=lambda: clock[0])
        b.restore_state(state)
        assert b._ewma["h1"] == pytest.approx(1.5)
        assert b._hb_ewma["h1"] == pytest.approx(0.4)
        # The sustained-condemnation AGE survived the clock change.
        assert 500.0 - b._above_since["h1"] == pytest.approx(30.0)
        assert b.resize_cost_s() == pytest.approx(12.0)
        b.restore_state(None)  # malformed input is a no-op
        b.restore_state({"ewma": "nope"})

    def test_integrity_vote_state_survives_takeover(self, tmp_path,
                                                    monkeypatch):
        """The acted-group watermark rides the snapshot with the strike
        counts: workers keep staging the same fingerprint on every
        heartbeat, so a takeover driver re-voting the identical
        (generation, step) group would double-count the strike and
        break the HOROVOD_INTEGRITY_CONFIRMATIONS contract."""
        from horovod_tpu.runner.elastic.discovery import (
            FixedHostDiscovery,
        )
        from horovod_tpu.runner.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.launch import Settings

        monkeypatch.setenv("HOROVOD_DRIVER_STATE_DIR", str(tmp_path))
        settings = Settings(
            num_proc=1, hosts=[], command=["true"], elastic=True,
            min_np=1, max_np=1, discovery_script=None)
        a = ElasticDriver(
            settings,
            discovery=FixedHostDiscovery([HostInfo("localhost", 1)]))
        a._integrity_acted_group = (2, 40)
        a._integrity_strikes["h1"] = 1
        a._server.quarantine_rank(1, "h1", generation=2, step=40,
                                  from_generation=1, from_step=30)
        a._store.save(a._snapshot_record())
        b = ElasticDriver(
            settings,
            discovery=FixedHostDiscovery([HostInfo("localhost", 1)]))
        assert b._prepare_takeover()
        assert b._integrity_acted_group == (2, 40)
        assert b._integrity_strikes == {"h1": 1}
        # The KV quarantine survives onto the successor's fresh server:
        # the acted-group watermark suppresses a re-vote, so without
        # this the condemned rank's replicas would be assembly-eligible
        # again (permanently, if the corrupt host died with driver A).
        q = b._server.quarantine_export()
        assert q["1"]["host"] == "h1" and q["1"]["generation"] == 2
        assert q["1"]["from_generation"] == 1 and q["1"]["from_step"] == 30

    def test_blacklist_cooldown_survives_restart(self):
        from horovod_tpu.runner.elastic.discovery import (
            FixedHostDiscovery,
            HostManager,
        )
        from horovod_tpu.runner.hosts import HostInfo

        m1 = HostManager(FixedHostDiscovery([HostInfo("a", 1)]),
                         cooldown_s=60.0)
        m1.blacklist("a")
        ages = m1.export_blacklist()
        assert 0.0 <= ages["a"] < 5.0
        m2 = HostManager(FixedHostDiscovery([HostInfo("a", 1)]),
                         cooldown_s=60.0)
        # Simulate 50s already served before the crash: the successor
        # must re-admit after ~10 more, not a fresh 60.
        m2.restore_blacklist({"a": 50.0})
        assert m2.is_blacklisted("a")
        m3 = HostManager(FixedHostDiscovery([HostInfo("a", 1)]),
                         cooldown_s=60.0)
        m3.restore_blacklist({"a": 61.0})  # already expired
        assert not m3.is_blacklisted("a")


# -- the worker orphan loop ---------------------------------------------------


class TestOrphanRejoin:
    def _ctx(self, monkeypatch, port, **env):
        from horovod_tpu.runner.elastic.worker import ElasticWorkerContext

        monkeypatch.setenv("HOROVOD_RENDEZVOUS_ADDR", "127.0.0.1")
        monkeypatch.setenv("HOROVOD_RENDEZVOUS_PORT", str(port))
        monkeypatch.setenv("HOROVOD_HOSTNAME", "hostA")
        monkeypatch.setenv("HOROVOD_KV_RETRIES", "1")
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        return ElasticWorkerContext

    def test_no_state_dir_means_head_203_path(self, monkeypatch):
        """A/B arm: with HOROVOD_DRIVER_STATE_DIR unset the orphan loop
        is disabled outright — the driver-loss deadline fires exactly as
        at HEAD, with zero rejoin probes."""
        from horovod_tpu.runner.network import free_port

        cls = self._ctx(monkeypatch, free_port(),
                        HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT="0.4")
        lost = []
        ctx = cls(on_driver_lost=lost.append)
        assert ctx.rejoin_timeout() == 0.0
        ctx.start_polling(interval=0.05)
        deadline = time.time() + 20
        while time.time() < deadline and not lost:
            time.sleep(0.05)
        ctx.stop_polling()
        assert lost and lost[0] >= 0.4

    def test_orphan_waits_past_lost_deadline_then_exits(
            self, monkeypatch, tmp_path):
        """Armed but no successor ever appears: the worker waits the
        loss deadline PLUS the rejoin budget, then gives up."""
        from horovod_tpu.runner.network import free_port

        cls = self._ctx(monkeypatch, free_port(),
                        HOROVOD_DRIVER_STATE_DIR=str(tmp_path),
                        HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT="0.4",
                        HOROVOD_DRIVER_REJOIN_TIMEOUT="1.0",
                        HOROVOD_DRIVER_REJOIN_PROBE_INTERVAL="0.1")
        lost = []
        t0 = time.monotonic()
        ctx = cls(on_driver_lost=lambda s: lost.append(
            (s, time.monotonic() - t0)))
        assert ctx.rejoin_timeout() == 1.0
        ctx.start_polling(interval=0.05)
        deadline = time.time() + 30
        while time.time() < deadline and not lost:
            time.sleep(0.05)
        ctx.stop_polling()
        assert lost, "orphan never gave up"
        silent_s, wall = lost[0]
        assert silent_s >= 1.4, lost  # lost deadline + rejoin budget

    def test_orphan_rejoins_successor_driver(self, monkeypatch, tmp_path):
        """The takeover path end to end at the worker layer: driver #1
        dies; driver #2 (higher epoch) seeds at the old generation,
        writes the endpoint record, publishes g+1 — the orphan repoints,
        adopts the epoch, arms the hosts-updated notification, and its
        heartbeats land on the NEW server."""
        from horovod_tpu.elastic.runner import notification_manager

        s1 = RendezvousServer()
        s1.seed(driver_epoch=1)
        s1.start()
        s1.publish_epoch("world", {"hostA": b"{}"})
        cls = self._ctx(monkeypatch, s1.port,
                        HOROVOD_DRIVER_STATE_DIR=str(tmp_path),
                        HOROVOD_DRIVER_EPOCH="1",
                        HOROVOD_WORLD_VERSION="1",
                        HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT="1.0",
                        HOROVOD_DRIVER_REJOIN_TIMEOUT="60",
                        HOROVOD_DRIVER_REJOIN_PROBE_INTERVAL="0.1")
        ctx = cls()
        notification_manager.clear()
        ctx.start_polling(interval=0.05)
        ctx.start_heartbeat(interval=0.2)
        try:
            s1.stop()  # driver #1 dies
            s2 = RendezvousServer()
            s2.seed(generation=1, driver_epoch=2)
            s2.start()
            store = driver_state.DriverStateStore(str(tmp_path), epoch=2)
            store.publish_endpoint("127.0.0.1", s2.port, 1)
            deadline = time.time() + 30
            while time.time() < deadline and ctx.driver_epoch != 2:
                time.sleep(0.05)
            assert ctx.driver_epoch == 2, "never repointed"
            assert os.environ["HOROVOD_RENDEZVOUS_PORT"] == str(s2.port)
            s2.publish_epoch("world", {"hostA": b'{"process_id": 0}'})
            deadline = time.time() + 20
            while (time.time() < deadline
                   and not notification_manager._pending):
                time.sleep(0.05)
            assert notification_manager._pending, "g+1 bump never armed"
            deadline = time.time() + 20
            while (time.time() < deadline
                   and s2.heartbeat_age("hostA") is None):
                time.sleep(0.05)
            assert s2.heartbeat_age("hostA") is not None
        finally:
            ctx.stop_polling()
            notification_manager.clear()
            try:
                s2.stop()
            except Exception:
                pass

    def test_stale_endpoint_record_is_ignored(self, monkeypatch,
                                              tmp_path):
        """The dead driver's OWN record (epoch <= the worker's) must
        never be followed — only a strictly higher epoch is a
        successor."""
        from horovod_tpu.runner.network import free_port

        cls = self._ctx(monkeypatch, free_port(),
                        HOROVOD_DRIVER_STATE_DIR=str(tmp_path),
                        HOROVOD_DRIVER_EPOCH="2")
        ctx = cls()
        store = driver_state.DriverStateStore(str(tmp_path), epoch=2)
        store.publish_endpoint("127.0.0.1", 1, 1)
        ctx._next_rejoin_probe = 0.0
        assert ctx._try_rejoin() is False
        assert ctx.driver_epoch == 2


# -- end-to-end: SIGKILL the driver mid-training ------------------------------

# Workers redirect their own stdout/stderr to per-host files at startup:
# their launcher-provided pipe dies WITH the driver, and a worker that
# prints into a readerless pipe would take EPIPE — the exact coupling a
# control-plane crash must not have.
_E2E_WORKER = '''
import os, sys
sys.path.insert(0, {repo_root!r})
host = os.environ["HOROVOD_HOSTNAME"]
tmp = os.environ["TEST_TMP"]
_fd = os.open(os.path.join(tmp, "worker-%s.log" % host),
              os.O_WRONLY | os.O_CREAT | os.O_APPEND)
os.dup2(_fd, 1)
os.dup2(_fd, 2)
sys.stdout = os.fdopen(1, "w", buffering=1)
sys.stderr = os.fdopen(2, "w", buffering=1)
print("pid=%d host=%s" % (os.getpid(), host), flush=True)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["HOROVOD_EVENT_LOG"] = os.path.join(
    tmp, "events-%s.jsonl" % host)
import jax
jax.config.update("jax_platforms", "cpu")
from horovod_tpu._jax_compat import force_cpu_devices
force_cpu_devices(1)
import time
import numpy as np
import optax
import horovod_tpu as hvd
from horovod_tpu import abort, process_world
from horovod_tpu.elastic import PeerShardedState, run as elastic_run
from horovod_tpu.optimizer import ReduceSpec, init_sharded_state

LR, MU, EPOCHS = 0.05, 0.9, 6
W0 = np.linspace(0.5, -0.5, 8).astype(np.float32)


def local_grad(w, e, r):
    rng = np.random.RandomState(1000 + 10 * e + r)
    A = rng.randn(16, 8).astype(np.float32)
    return ((A.T @ (A @ w)) / 16.0).astype(np.float32)


spec = ReduceSpec(
    inner=optax.sgd(LR, momentum=MU), op="average", compression=None,
    prescale_factor=1.0, postscale_factor=1.0, process_set=None,
    num_groups=0, fusion_threshold_bytes=None, backward_passes_per_step=1,
    sync_mode="sharded")
n0 = process_world.size()
params = {{"w": W0.copy()}}
state = PeerShardedState(
    params=params, opt_state=init_sharded_state(spec, params, world_size=n0),
    sharded_optimizer=spec, epoch=0)


def durable_restore():
    # Registered ONLY to prove it never runs: the takeover recovery must
    # land on the peer rung with zero durable reads.
    print("DURABLE_RESTORE_USED", flush=True)
    raise RuntimeError("durable restore must not run in this scenario")


state.register_durable_restore(durable_restore)


@elastic_run
def train(state):
    from horovod_tpu.parallel.hierarchical import _default_native_world

    while state.epoch < EPOCHS:
        e = state.epoch
        if e >= 3:
            # Gate: epochs 3+ run only AFTER the takeover driver has
            # re-formed the world at g+1 (the test SIGKILLs driver #1
            # once both ranks committed epoch 2). The abort poll is what
            # breaks the wait: the successor posts abort/<g> before
            # publishing g+1, driving this worker into the recovery
            # ladder — deterministically, at a commit-consistent point.
            deadline = time.time() + 180
            while int(os.environ.get("HOROVOD_WORLD_VERSION", "0")) < 2:
                abort.raise_if_aborted()
                if time.time() > deadline:
                    print("GATE TIMED OUT", flush=True)
                    os._exit(9)
                time.sleep(0.05)
        r, n = process_world.rank(), process_world.size()
        w = np.asarray(state.params["w"])
        g = local_grad(w, e, r)
        if n > 1:
            world = _default_native_world()
            g = np.asarray(world.allreduce(g, name="grad.%d" % e,
                                           op="average"),
                           dtype=np.float32)
        tdef = jax.tree.structure(state.opt_state)
        trace = np.asarray(jax.tree.leaves(state.opt_state)[0])
        n_axis, s = trace.shape
        g_rows = np.pad(g, (0, n_axis * s - g.size)).reshape(n_axis, s)
        trace = (MU * trace + g_rows).astype(np.float32)
        w = (w - LR * trace.reshape(-1)[: w.size]).astype(np.float32)
        state.opt_state = jax.tree.unflatten(tdef, [trace])
        state.params = {{"w": w}}
        print("rank=%d epoch=%d np=%d gen=%s w0=%.6f" % (
            r, e, n, os.environ.get("HOROVOD_WORLD_VERSION", "?"),
            float(w[0])), flush=True)
        state.epoch = e + 1
        state.commit()
    return state.epoch


done = train(state)
print("host=%s finished at epoch %d" % (host, done), flush=True)
'''

_DRIVER_RUNNER = '''
import os, sys
sys.path.insert(0, {repo_root!r})
os.environ["HOROVOD_EVENT_LOG"] = os.path.join(
    os.environ["TEST_TMP"], "events-driver.jsonl")
from horovod_tpu.runner.elastic.driver import run_elastic
from horovod_tpu.runner.launch import Settings

settings = Settings(
    num_proc=2, hosts=[],
    command=[sys.executable, os.environ["TEST_WORKER"]],
    cpu_mode=True, elastic=True, min_np=2, max_np=2,
    discovery_script=os.environ["TEST_DISCOVER"],
    elastic_timeout=120.0, env={{}})
print("DRIVER PID=%d" % os.getpid(), flush=True)
sys.exit(run_elastic(settings, sink=lambda s: print(s, flush=True)))
'''


def _expected_trajectory():
    """The uninterrupted run: all 6 epochs on the 2-rank averaged
    gradient (both workers survive the driver crash). Any loss of the
    momentum state across the takeover diverges from this immediately."""
    lr, mu = 0.05, 0.9

    def local_grad(w, e, r):
        rng = np.random.RandomState(1000 + 10 * e + r)
        A = rng.randn(16, 8).astype(np.float32)
        return ((A.T @ (A @ w)) / 16.0).astype(np.float32)

    w = np.linspace(0.5, -0.5, 8).astype(np.float32)
    m = np.zeros(8, np.float32)
    out = {}
    for e in range(6):
        g = ((local_grad(w, e, 0) + local_grad(w, e, 1)) / 2.0
             ).astype(np.float32)
        m = (mu * m + g).astype(np.float32)
        w = (w - lr * m).astype(np.float32)
        out[e] = w.copy()
    return out


def _write_cluster(tmp_path):
    import stat

    worker = tmp_path / "failover_worker.py"
    worker.write_text(_E2E_WORKER.format(repo_root=REPO_ROOT))
    runner = tmp_path / "driver_runner.py"
    runner.write_text(_DRIVER_RUNNER.format(repo_root=REPO_ROOT))
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("localhost\n127.0.0.1\n")
    discover = tmp_path / "discover.sh"
    discover.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    discover.chmod(discover.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ)
    env.update({
        "TEST_TMP": str(tmp_path),
        "TEST_WORKER": str(worker),
        "TEST_DISCOVER": str(discover),
        "HOROVOD_DRIVER_STATE_DIR": str(tmp_path / "driver-state"),
        "HOROVOD_DRIVER_STATE_REFRESH": "0.5",
        "HOROVOD_DRIVER_REJOIN_TIMEOUT": "120",
        "HOROVOD_DRIVER_REJOIN_PROBE_INTERVAL": "0.2",
        "HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT": "2.0",
        "HOROVOD_KV_RETRIES": "1",
        "HOROVOD_RECOVERY_BACKOFF_MAX": "0.2",
        "HOROVOD_ABORT_POLL_INTERVAL": "0.2",
        "JAX_PLATFORMS": "cpu",
    })
    return runner, env


def _spawn_driver(runner, env):
    return subprocess.Popen(
        [sys.executable, str(runner)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        start_new_session=True)


def _wait_for_epoch(tmp_path, epoch, hosts=("localhost", "127.0.0.1"),
                    timeout=240):
    deadline = time.time() + timeout
    needle = re.compile(rf"epoch={epoch} ")
    while time.time() < deadline:
        if all(
            (tmp_path / f"worker-{h}.log").exists()
            and needle.search((tmp_path / f"worker-{h}.log").read_text())
            for h in hosts
        ):
            return
        time.sleep(0.2)
    logs = {h: (tmp_path / f"worker-{h}.log").read_text()
            if (tmp_path / f"worker-{h}.log").exists() else "<missing>"
            for h in hosts}
    raise AssertionError(f"epoch {epoch} never reached: {logs}")


class TestDriverFailoverE2E:
    @pytest.mark.slow
    def test_sigkill_driver_workers_rejoin_at_g_plus_1_on_peer_rung(
            self, tmp_path):
        """The acceptance e2e: SIGKILL the driver once both workers have
        committed epoch 2; a supervisor relaunch takes over from the
        snapshot; both workers rejoin at generation g+1 WITHOUT a
        process restart; recovery lands on the peer rung with zero
        durable reads; and the weight trajectory matches the
        uninterrupted 2-rank run step for step."""
        runner, env = _write_cluster(tmp_path)
        d1 = _spawn_driver(runner, env)
        d2 = None
        try:
            _wait_for_epoch(tmp_path, 2)
            # Let the epoch-2 commits' replica PUTs + neighbor pulls
            # settle so both ranks hold a complete in-memory set.
            time.sleep(1.5)
            faults.kill_driver(d1.pid)
            d1.communicate(timeout=30)
            assert d1.returncode == -signal.SIGKILL
            # The supervisor relaunch.
            d2 = _spawn_driver(runner, env)
            out2, _ = d2.communicate(timeout=420)
            assert d2.returncode == 0, out2
        finally:
            for proc in (d1, d2):
                if proc is not None and proc.poll() is None:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)

        logs = {h: (tmp_path / f"worker-{h}.log").read_text()
                for h in ("localhost", "127.0.0.1")}
        expected = _expected_trajectory()
        pids_by_host = {}
        for host, text in logs.items():
            assert "finished at epoch 6" in text, (host, text)
            assert "DURABLE_RESTORE_USED" not in text, (host, text)
            assert "GATE TIMED OUT" not in text, (host, text)
            pids = re.findall(r"^pid=(\d+) ", text, re.M)
            pids_by_host[host] = pids
            # No process restart: one worker process per host, ever.
            assert len(set(pids)) == 1, (host, pids)
            seen = {}
            for match in re.finditer(
                    r"rank=(\d+) epoch=(\d+) np=(\d+) gen=(\d+) "
                    r"w0=(-?[0-9.]+)", text):
                e, np_, gen = (int(match.group(2)), int(match.group(3)),
                               int(match.group(4)))
                w0 = float(match.group(5))
                seen.setdefault(e, []).append((np_, gen, w0))
            for e in range(6):
                assert e in seen, (host, e, sorted(seen))
                for np_, gen, w0 in seen[e]:
                    # Both workers survive: np=2 for EVERY epoch, and
                    # the trajectory is the uninterrupted one.
                    assert np_ == 2, (host, e, np_)
                    assert abs(w0 - float(expected[e][0])) < 2e-4, (
                        host, e, w0, float(expected[e][0]))
            # Generation fence: pre-crash epochs at g, post-takeover at
            # g+1 (epoch 2 may legitimately replay at either side).
            pre = {gen for _, gen, _ in seen[0]}
            post = {gen for _, gen, _ in seen[5]}
            assert max(post) == max(pre) + 1, (host, pre, post)

        # The survivors' journals tell the peer-rung story: the ladder
        # touched 'peer', never 'durable', with no fall-through.
        for host in ("localhost", "127.0.0.1"):
            events = [json.loads(l) for l in (
                tmp_path / f"events-{host}.jsonl").read_text().splitlines()]
            rungs = [e["rung"] for e in events if e["event"] == "recovery"]
            assert "peer" in rungs, (host, rungs)
            assert "durable" not in rungs, (host, rungs)
            assert any(e["event"] == "peer_restore" for e in events), host
            assert not any(e["event"] == "peer_fallback" for e in events)
            assert any(e["event"] == "driver_rejoin"
                       and e.get("driver_epoch") == 2
                       for e in events), host

        # The driver journal: a takeover at epoch 2 adopting both hosts.
        devents = [json.loads(l) for l in (
            tmp_path / "events-driver.jsonl").read_text().splitlines()]
        takeovers = [e for e in devents if e["event"] == "driver_takeover"]
        assert takeovers, devents
        assert sorted(takeovers[-1]["adopted"]) == ["127.0.0.1",
                                                    "localhost"]
        assert takeovers[-1]["driver_epoch"] == 2
        starts = [e for e in devents if e["event"] == "driver_start"]
        assert any(e.get("takeover") for e in starts)
        assert any(e["event"] == "job_complete" for e in devents)

    @pytest.mark.slow
    def test_sigstopped_stale_driver_stands_down_superseded(
            self, tmp_path):
        """Split-brain: driver #1 is SIGSTOP'd (not dead) through a
        takeover; when resumed it must discover the higher-epoch
        snapshot on its next refresh and exit EXIT_DRIVER_SUPERSEDED
        WITHOUT terminating the workers the successor adopted — and the
        job must still complete under driver #2."""
        runner, env = _write_cluster(tmp_path)
        d1 = _spawn_driver(runner, env)
        d2 = None
        try:
            _wait_for_epoch(tmp_path, 2)
            time.sleep(1.5)
            os.kill(d1.pid, signal.SIGSTOP)  # hung, not crashed
            d2 = _spawn_driver(runner, env)
            # Wait until the successor owns the state dir (epoch 2 on
            # disk) before resuming the zombie.
            deadline = time.time() + 240
            while time.time() < deadline:
                rec = driver_state.read_endpoint(
                    str(tmp_path / "driver-state"))
                if rec is not None and rec["driver_epoch"] >= 2:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError("successor never published")
            os.kill(d1.pid, signal.SIGCONT)
            out1, _ = d1.communicate(timeout=120)
            assert d1.returncode == EXIT_DRIVER_SUPERSEDED, (
                d1.returncode, out1)
            # Standing down touched nothing: the job completes under
            # driver #2 with the same continuity contract as above.
            out2, _ = d2.communicate(timeout=420)
            assert d2.returncode == 0, out2
        finally:
            for proc in (d1, d2):
                if proc is not None and proc.poll() is None:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        for host in ("localhost", "127.0.0.1"):
            text = (tmp_path / f"worker-{host}.log").read_text()
            assert "finished at epoch 6" in text, (host, text)
            pids = re.findall(r"^pid=(\d+) ", text, re.M)
            assert len(set(pids)) == 1, (host, pids)
        devents = [json.loads(l) for l in (
            tmp_path / "events-driver.jsonl").read_text().splitlines()]
        assert any(e["event"] == "driver_superseded" for e in devents)
